"""Batch inference (ISSUE 20): sharded manifests, the exactly-once
shard ledger, the driver's cooperative 429/Retry-After backoff, the
congestion-derived shed Retry-After stamp, live weight swap, and the
`jobs queue` PROGRESS plumbing.

The end-to-end crash/resume story (driver killed mid-commit, replica
killed mid-shard, live swap under interactive load) lives in the
`batch_resume` chaos scenario (tests/unit/test_chaos.py); this file
pins the unit seams."""
from __future__ import annotations

import json
import os
import threading

import pytest

from skypilot_tpu.batch import manifest as manifest_lib
from skypilot_tpu.batch import runner as runner_lib
from skypilot_tpu.serve import http_protocol


def _write_input(path, n_rows):
    with open(path, 'w', encoding='utf-8') as f:
        for i in range(n_rows):
            f.write(json.dumps({'prompt_ids': [i + 1, 2, 3]}) + '\n')


class TestManifest:

    def test_build_and_reload_roundtrip(self, tmp_path):
        src = str(tmp_path / 'input.jsonl')
        _write_input(src, 10)
        run_dir = str(tmp_path / 'run')
        built = manifest_lib.build_manifest(src, run_dir, num_shards=3)
        # Contiguous split: 10 rows over 3 shards -> 4, 3, 3.
        assert built.shard_rows == [4, 3, 3]
        assert built.total_rows == 10
        reloaded = manifest_lib.Manifest(run_dir)
        assert reloaded.num_shards == 3
        assert reloaded.shard_rows == built.shard_rows
        rows = list(reloaded.rows(0))
        assert [idx for idx, _ in rows] == [0, 1, 2, 3]
        assert rows[0][1]['prompt_ids'] == [1, 2, 3]
        # Shard 1 continues where shard 0 stopped (source order).
        assert next(iter(reloaded.rows(1)))[1]['prompt_ids'][0] == 5
        with pytest.raises(ValueError, match='out of range'):
            list(reloaded.rows(3))

    def test_more_shards_than_rows_collapses(self, tmp_path):
        src = str(tmp_path / 'input.jsonl')
        _write_input(src, 2)
        built = manifest_lib.build_manifest(
            src, str(tmp_path / 'run'), num_shards=8)
        assert built.num_shards == 2

    def test_bad_inputs_rejected(self, tmp_path):
        bad = str(tmp_path / 'bad.jsonl')
        with open(bad, 'w', encoding='utf-8') as f:
            f.write(json.dumps({'no_prompt': 1}) + '\n')
        with pytest.raises(ValueError, match='prompt'):
            manifest_lib.build_manifest(bad, str(tmp_path / 'r1'))
        with open(bad, 'w', encoding='utf-8') as f:
            f.write('not json\n')
        with pytest.raises(ValueError, match='bad JSON'):
            manifest_lib.build_manifest(bad, str(tmp_path / 'r2'))
        empty = str(tmp_path / 'empty.jsonl')
        open(empty, 'w', encoding='utf-8').close()
        with pytest.raises(ValueError, match='no input rows'):
            manifest_lib.build_manifest(empty, str(tmp_path / 'r3'))
        with pytest.raises(ValueError, match='not a batch manifest'):
            manifest_lib.Manifest(str(tmp_path))


class TestShardLedger:

    def _built(self, tmp_path, n_rows=6, num_shards=2):
        src = str(tmp_path / 'input.jsonl')
        _write_input(src, n_rows)
        run_dir = str(tmp_path / 'run')
        return (manifest_lib.build_manifest(src, run_dir,
                                            num_shards=num_shards),
                run_dir)

    def test_replay_resumes_committed_rows(self, tmp_path):
        manifest, run_dir = self._built(tmp_path)
        ledger = manifest_lib.ShardLedger(run_dir)
        ledger.commit_row(0, 0, {'tokens': [9]})
        ledger.commit_row(0, 1, {'tokens': [9]})
        ledger.commit_row(0, 2, {'tokens': [9]})
        ledger.finish_shard(0)
        ledger.commit_row(1, 0, {'tokens': [9]})
        ledger.close()
        # A fresh ledger (the resumed driver) sees exactly that state.
        done_rows, done_shards = manifest_lib.ShardLedger(
            run_dir).replay()
        assert done_rows == {(0, 0), (0, 1), (0, 2), (1, 0)}
        assert done_shards == {0}
        progress = manifest_lib.ShardLedger(run_dir).progress(manifest)
        assert progress == {'rows_done': 4, 'rows_total': 6,
                            'shards_done': 1, 'shards_total': 2}

    def test_torn_ledger_tail_rerun_not_lost(self, tmp_path):
        _, run_dir = self._built(tmp_path)
        ledger = manifest_lib.ShardLedger(run_dir)
        ledger.commit_row(0, 0, {'tokens': [9]})
        ledger.close()
        # A crash mid-append leaves a torn trailing line: the row it
        # named never enters the done-set (it re-runs; never lost).
        with open(os.path.join(run_dir, manifest_lib.LEDGER_FILE),
                  'a', encoding='utf-8') as f:
            f.write('{"kind": "row", "shard": 0, "row_i')
        done_rows, _ = manifest_lib.ShardLedger(run_dir).replay()
        assert done_rows == {(0, 0)}

    def test_finalize_dedupes_half_committed_row(self, tmp_path):
        manifest, run_dir = self._built(tmp_path)
        ledger = manifest_lib.ShardLedger(run_dir)
        for shard in range(2):
            for row_idx, _ in manifest.rows(shard):
                ledger.commit_row(shard, row_idx, {'tokens': [1]})
        # The crash seam: output appended, ledger record lost -> the
        # resumed driver re-ran the row, so the output holds it TWICE.
        ledger.commit_row(1, 2, {'tokens': [1]})
        summary = ledger.finalize(manifest)
        assert summary == {'rows': 6, 'duplicates_dropped': 1}
        out = manifest_lib.ShardLedger(run_dir).output_rows(manifest)
        keys = [(r['shard'], r['row_idx']) for r in out]
        assert len(keys) == 6 and len(set(keys)) == 6

    def test_finalize_refuses_missing_rows(self, tmp_path):
        manifest, run_dir = self._built(tmp_path)
        ledger = manifest_lib.ShardLedger(run_dir)
        ledger.commit_row(0, 0, {'tokens': [1]})
        with pytest.raises(RuntimeError, match='resume before'):
            ledger.finalize(manifest)


class TestDriverBackoff:

    def test_retry_after_honored_then_success(self, tmp_path):
        """The cooperative contract: a 429 + Retry-After from the shed
        path makes the driver back off and retry, not fail the row."""
        import http.server

        import requests

        src = str(tmp_path / 'input.jsonl')
        _write_input(src, 1)
        run_dir = str(tmp_path / 'run')
        manifest_lib.build_manifest(src, run_dir, num_shards=1)
        hits = []

        class Handler(http.server.BaseHTTPRequestHandler):

            def do_POST(self):  # noqa: N802
                hits.append(self.path)
                self.rfile.read(
                    int(self.headers.get('Content-Length', 0)))
                if len(hits) == 1:
                    self.send_response(429)
                    self.send_header('Retry-After', '0')
                    self.end_headers()
                    return
                body = json.dumps({'tokens': [[7, 8]],
                                   'weight_version': 3,
                                   'latency_ms': 1.0}).encode()
                self.send_response(200)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        httpd = http.server.ThreadingHTTPServer(('127.0.0.1', 0),
                                                Handler)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            job = runner_lib.BatchInferJob(
                run_dir, f'http://127.0.0.1:{httpd.server_port}',
                max_new_tokens=2, job_id=None)
            result = job._post_row(  # pylint: disable=protected-access
                requests.Session(), {'prompt_ids': [1, 2, 3]})
        finally:
            httpd.shutdown()
            httpd.server_close()
        assert result['tokens'] == [[7, 8]]
        assert job.retries == 1
        assert len(hits) == 2

    def test_env_knobs_parse_with_fallbacks(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_BATCH_INFLIGHT', '12')
        monkeypatch.setenv('SKYTPU_BATCH_MAX_RETRIES', 'nope')
        monkeypatch.setenv('SKYTPU_BATCH_RETRY_AFTER_CAP_S', '2.5')
        assert runner_lib.default_inflight() == 12
        assert runner_lib.max_retries() == 16  # bad value -> default
        assert runner_lib.retry_after_cap_s() == 2.5
        monkeypatch.setenv('SKYTPU_BATCH_INFLIGHT', '-3')
        assert runner_lib.default_inflight() == 4  # non-positive


class TestShedRetryAfter:

    def test_queue_wait_p50_unit_pin(self):
        """Bucket labels are seconds; the estimate is the upper edge of
        the bucket holding the median — EXACT values, a unit mix-up
        (ms vs s) breaks this pin."""
        from skypilot_tpu.serve import qos
        hist = {'<0.5s': 3, '<1.0s': 2, '>=5.0s': 0}
        assert qos.queue_wait_p50(hist) == 0.5
        hist = {'<0.5s': 1, '<2.0s': 1, '<4.0s': 2}
        assert qos.queue_wait_p50(hist) == 2.0
        # Median in the open-ended bucket: largest finite edge.
        assert qos.queue_wait_p50({'<0.5s': 1, '>=5.0s': 9}) == 0.5
        assert qos.queue_wait_p50(None) is None
        assert qos.queue_wait_p50({}) is None
        assert qos.queue_wait_p50({'weird': 1}) is None
        assert qos.queue_wait_p50({'<0.5s': -1}) is None

    def test_shed_stamp_tracks_worst_replica_p50(self):
        """The 429 Retry-After stamp: worst ready-replica median queue
        wait, rounded UP to whole seconds (floor 1s); static default
        1s when no replica reports a histogram."""
        from skypilot_tpu.serve import load_balancer as lb_lib
        from skypilot_tpu.serve import router as router_lib
        lb = lb_lib.SkyServeLoadBalancer(
            'http://127.0.0.1:1',
            router=router_lib.Router(threshold=10))
        lb.set_replicas([{'url': 'http://a', 'role': 'mixed'},
                         {'url': 'http://b', 'role': 'mixed'}])
        assert lb.shed_retry_after_s() == 1
        lb.set_replicas([
            {'url': 'http://a', 'role': 'mixed',
             'queue_wait_p50': 0.3},
            {'url': 'http://b', 'role': 'mixed',
             'queue_wait_p50': 2.4},
        ])
        assert lb.shed_retry_after_s() == 3  # ceil(2.4), worst wins


class TestWeightSwap:

    def test_route_registered(self):
        assert http_protocol.WEIGHTS_SWAP == '/weights_swap'
        assert http_protocol.WEIGHTS_SWAP in http_protocol.REPLICA_PATHS

    def test_swap_requires_continuous_batching(self):
        from skypilot_tpu.serve import model_server
        srv = model_server.ModelServer('tiny', max_len=32, max_batch=1)
        with pytest.raises(ValueError, match='continuous-batching'):
            srv.weights_swap({'checkpoint_dir': '/nowhere'})

    def test_swap_validates_request(self, tmp_path):
        from skypilot_tpu.serve import model_server
        srv = model_server.ModelServer(
            'tiny', max_len=32, max_batch=1, continuous_batching=True,
            kv_pages=8, page_size=8, prefill_chunk=16)
        try:
            with pytest.raises(ValueError, match='checkpoint_dir'):
                srv.weights_swap({})
            with pytest.raises(ValueError, match='no checkpoint'):
                srv.weights_swap({'checkpoint_dir': str(tmp_path)})
            # swap_params is the engine half: epoch bumps per swap and
            # the KV pool is untouched (no pages dropped by a swap).
            engine = srv._engine  # pylint: disable=protected-access
            before = engine.stats()
            assert before['weight_epoch'] == 0
            assert engine.swap_params(srv.params) == 1
            assert engine.swap_params(srv.params) == 2
            after = engine.stats()
            assert after['weight_epoch'] == 2
            assert after['kv_pages_used'] == before['kv_pages_used']
        finally:
            srv.close()


class TestJobsProgressColumn:

    def test_set_batch_progress_roundtrip(self, monkeypatch, tmp_path):
        monkeypatch.setenv('SKYTPU_MANAGED_JOB_DB',
                           str(tmp_path / 'mj.db'))
        from skypilot_tpu.jobs import state as jobs_state
        job_id = jobs_state.allocate_job_id('batchy')
        records = jobs_state.get_job_records(job_id)
        # Additive migration: the column exists and starts empty.
        assert records[0]['batch_progress'] is None
        jobs_state.set_batch_progress(job_id, 0,
                                      '1/3 shards (4/10 rows)')
        records = jobs_state.get_job_records(job_id)
        assert records[0]['batch_progress'] == '1/3 shards (4/10 rows)'
