"""Page-pool allocator + prefix cache unit tests (serve/cache_manager):
alloc/free/pin/COW semantics, exhaustion, LRU eviction, and no-leak
accounting across the engine's cancel/TTL/shutdown paths."""
from __future__ import annotations

import pytest

from skypilot_tpu.serve import cache_manager


class TestPagePool:

    def test_alloc_free_roundtrip(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=4)
        assert pool.capacity == 7           # page 0 reserved (null)
        pages = pool.alloc(3)
        assert len(pages) == 3
        assert cache_manager.NULL_PAGE not in pages
        assert pool.used_count == 3 and pool.free_count == 4
        pool.decref(pages)
        assert pool.used_count == 0 and pool.free_count == 7

    def test_exhaustion_raises_and_is_all_or_nothing(self):
        pool = cache_manager.PagePool(n_pages=4, page_size=4)
        pool.alloc(2)
        with pytest.raises(cache_manager.PagesExhausted):
            pool.alloc(2)                   # only 1 free
        # The failed alloc must not have consumed the last page.
        assert pool.free_count == 1

    def test_refcount_sharing(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=4)
        (page,) = pool.alloc(1)
        pool.incref([page])                 # a second slot adopts it
        pool.decref([page])
        assert pool.used_count == 1         # still held by one slot
        pool.decref([page])
        assert pool.used_count == 0

    def test_pin_keeps_page_resident_at_ref_zero(self):
        pool = cache_manager.PagePool(n_pages=4, page_size=4)
        (page,) = pool.alloc(1)
        pool.pin(page)
        pool.decref([page])
        assert pool.used_count == 1 and pool.pinned_count == 1
        pool.unpin(page)
        assert pool.used_count == 0 and pool.pinned_count == 0

    def test_cow_private_page_is_in_place(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=4)
        (page,) = pool.alloc(1)
        writable, needs_copy = pool.cow(page)
        assert writable == page and needs_copy is False

    def test_cow_shared_page_allocates_fresh(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=4)
        (page,) = pool.alloc(1)
        pool.incref([page])                 # shared by two holders
        writable, needs_copy = pool.cow(page)
        assert needs_copy is True and writable != page
        assert pool.refcount(page) == 1     # shared ref dropped
        assert pool.refcount(writable) == 1

    def test_double_free_and_bad_ops_rejected(self):
        pool = cache_manager.PagePool(n_pages=4, page_size=4)
        (page,) = pool.alloc(1)
        pool.decref([page])
        with pytest.raises(ValueError):
            pool.decref([page])
        with pytest.raises(ValueError):
            pool.pin(page)                  # unallocated
        with pytest.raises(ValueError):
            pool.unpin(page)

    def test_validation(self):
        with pytest.raises(ValueError):
            cache_manager.PagePool(n_pages=1, page_size=4)
        with pytest.raises(ValueError):
            cache_manager.PagePool(n_pages=8, page_size=0)


class TestChunkHashes:

    def test_full_pages_only_and_chain_property(self):
        h1 = cache_manager.chunk_hashes([1, 2, 3, 4, 5, 6, 7], 4)
        assert len(h1) == 1                 # one full page of 4
        h2 = cache_manager.chunk_hashes([1, 2, 3, 4, 9, 9, 9, 9], 4)
        assert h2[0] == h1[0]               # same first page
        # The chain: page 2 differs if page 1 differed.
        a = cache_manager.chunk_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
        b = cache_manager.chunk_hashes([9, 2, 3, 4, 5, 6, 7, 8], 4)
        assert a[1] != b[1]

    def test_short_prompt_no_pages(self):
        assert cache_manager.chunk_hashes([1, 2, 3], 4) == []


class TestPrefixCache:

    def test_match_increfs_and_counts(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=2)
        cache = cache_manager.PrefixCache(pool)
        pages = pool.alloc(2)
        hashes = cache_manager.chunk_hashes([1, 2, 3, 4], 2)
        cache.register(hashes, pages)
        pool.decref(pages)                  # owner finished; pins hold
        matched = cache.match(hashes)
        assert matched == pages
        assert cache.hits == 2 and cache.misses == 0
        assert pool.refcount(pages[0]) == 1  # held for the adopter
        miss = cache.match(cache_manager.chunk_hashes([9, 9], 2))
        assert miss == [] and cache.misses == 1

    def test_partial_chain_match(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=2)
        cache = cache_manager.PrefixCache(pool)
        pages = pool.alloc(2)
        cache.register(cache_manager.chunk_hashes([1, 2, 3, 4], 2),
                       pages)
        pool.decref(pages)
        # Shares page 1, diverges in page 2 (mid-prompt divergence).
        matched = cache.match(
            cache_manager.chunk_hashes([1, 2, 9, 9], 2))
        assert matched == pages[:1]
        pool.decref(matched)

    def test_lru_eviction_skips_referenced_pages(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=2)
        cache = cache_manager.PrefixCache(pool)
        a = pool.alloc(1)
        b = pool.alloc(1)
        cache.register([111], a)
        cache.register([222], b)
        pool.decref(b)                      # only b is idle
        # a is oldest but still referenced -> eviction must skip it.
        released = cache.evict(1)
        assert released == 1
        assert len(cache) == 1
        assert pool.refcount(a[0]) == 1     # untouched

    def test_evictable_counts_idle_only(self):
        pool = cache_manager.PagePool(n_pages=8, page_size=2)
        cache = cache_manager.PrefixCache(pool)
        a = pool.alloc(1)
        cache.register([1], a)
        assert cache.evictable() == 0       # ref still held
        pool.decref(a)
        assert cache.evictable() == 1


class TestPagedKVManager:

    def test_pages_needed(self):
        mgr = cache_manager.PagedKVManager(16, 4, slots=2)
        # prompt 5 + 4 new: positions 0..7 -> 2 pages of 4.
        assert mgr.pages_needed(5, 4) == 2
        assert mgr.pages_needed(1, 1) == 1
        assert mgr.pages_needed(4, 5) == 2

    def test_plan_commit_release_no_leak(self):
        mgr = cache_manager.PagedKVManager(16, 4, slots=2)
        plan = mgr.plan_admission(list(range(10)), 4)
        assert len(plan.row) == mgr.pages_needed(10, 4)
        mgr.commit(0, plan)
        assert mgr.pool.used_count == len(plan.row)
        mgr.release(0)
        assert mgr.pool.used_count == 0
        mgr.release(0)                      # idempotent

    def test_exhaustion_releases_matched_pages(self):
        mgr = cache_manager.PagedKVManager(6, 2, slots=2)  # 5 usable
        plan = mgr.plan_admission([1, 2, 3, 4, 5], 2)      # 3 pages
        mgr.commit(0, plan)
        mgr.register_prefix(plan)
        mgr.release(0)                      # pages pinned, not leaked
        used_before = mgr.pool.used_count
        # Same prefix matches 2 pages, but the fresh remainder cannot
        # fit -> the matched refs must be released on failure.
        with pytest.raises(cache_manager.PagesExhausted):
            mgr.plan_admission([1, 2, 3, 4, 5] + [7] * 6, 2)
        assert mgr.pool.used_count == used_before
        for page in plan.row[:2]:
            assert mgr.pool.refcount(page) == 0

    def test_eviction_under_pressure(self):
        mgr = cache_manager.PagedKVManager(6, 2, slots=2)   # 5 usable
        plan = mgr.plan_admission([1, 2, 3, 4], 2)          # 3 pages
        mgr.commit(0, plan)
        mgr.register_prefix(plan)           # 1 full page pinned
        mgr.release(0)
        assert mgr.pool.free_count == 4     # 1 held by the pin
        # A 5-page request forces the prefix entry out.
        plan2 = mgr.plan_admission([9] * 8, 3, prefix_ok=False)
        assert len(plan2.row) == 5
        mgr.commit(1, plan2)
        mgr.release(1)
        assert mgr.pool.used_count == 0

    def test_release_all_clears_pins(self):
        mgr = cache_manager.PagedKVManager(16, 2, slots=2)
        plan = mgr.plan_admission([1, 2, 3, 4, 5], 2)
        mgr.commit(0, plan)
        mgr.register_prefix(plan)
        mgr.release_all()
        assert mgr.pool.used_count == 0
        assert mgr.pool.pinned_count == 0

    def test_stats_shape(self):
        mgr = cache_manager.PagedKVManager(8, 4, slots=2)
        stats = mgr.stats()
        for key in ('kv_pages_total', 'kv_pages_used', 'kv_pages_free',
                    'kv_pages_pinned', 'page_size',
                    'prefix_cache_entries', 'prefix_cache_hits',
                    'prefix_cache_misses'):
            assert key in stats
        assert stats['kv_pages_total'] == 7
