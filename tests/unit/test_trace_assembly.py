"""Distributed trace assembly tests (ISSUE 11).

Span segment export (identity + attempt tagging), the SegmentStore,
causal assembly, waterfall rendering, Chrome-trace export, and the
constant process-identity labels on the metrics exposition.
"""
from __future__ import annotations

import json
import time

from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import traces as traces_lib
from skypilot_tpu.observability import tracing


def _span(rid, attempt=None, routed_role=None):
    span = tracing.RequestSpan(rid)
    span.routed_role = routed_role
    span.attempt = attempt
    span.mark_admitted()
    span.mark_token()
    span.mark_token()
    span.finish('ok')
    return span


class TestSegmentExport:

    def test_span_segment_carries_identity_and_phases(self):
        span = _span('req1', attempt=1, routed_role='decode')
        seg = span.segment({'process': 'replica', 'replica_id': 3,
                            'role': 'decode'})
        assert seg['request_id'] == 'req1'
        assert seg['process'] == 'replica'
        assert seg['replica_id'] == 3
        assert seg['attempt'] == 1
        assert seg['name'] == 'engine'
        assert seg['start'] == span.submit_wall
        assert seg['duration_ms'] is not None
        names = [p['name'] for p in seg['phases']]
        assert 'decode' in names

    def test_store_export_filters(self):
        store = tracing.SpanStore()
        t0 = time.time()
        store.add(_span('a'))
        store.add(_span('b'))
        store.add(_span('c'))
        assert [s['request_id'] for s in store.export()] == \
            ['a', 'b', 'c']
        assert [s['request_id']
                for s in store.export(request_id='b')] == ['b']
        assert store.export(since=t0 + 3600) == []
        assert len(store.export(limit=2)) == 2
        # Identity tags ride every exported segment.
        [seg] = store.export({'replica_id': 9}, request_id='a')
        assert seg['replica_id'] == 9

    def test_attempt_disambiguates_retried_request_id(self):
        """The LB's one-shot retry reuses the request id on a second
        replica: with attempt tags the two segments stay distinct."""
        first = _span('same-rid', attempt=0).segment(
            {'replica_id': 1})
        retry = _span('same-rid', attempt=1).segment(
            {'replica_id': 2})
        merged = traces_lib.assemble([retry, first])
        assert [(s['replica_id'], s['attempt']) for s in merged] == \
            [(1, 0), (2, 1)]

    def test_segment_store(self):
        store = tracing.SegmentStore(maxlen=2)
        for i in range(3):
            store.add({'request_id': f'r{i}', 'start': float(i),
                       'name': 'lb'})
        assert len(store) == 2                       # bounded
        assert [s['request_id'] for s in store.export()] == \
            ['r1', 'r2']
        assert store.export(request_id='r2')[0]['start'] == 2.0
        assert store.export(since=2.0)[0]['request_id'] == 'r2'

    def test_parse_span_query(self):
        parsed = tracing.parse_span_query(
            'since=12.5&request_id=abc&limit=3')
        assert parsed == {'since': 12.5, 'request_id': 'abc',
                          'limit': 3}
        assert tracing.parse_span_query('') == {}
        assert tracing.parse_span_query('since=bogus') == {}


class TestAssembly:

    def _segments(self):
        t0 = 1000.0
        return [
            {'request_id': 'r', 'process': 'replica', 'replica_id': 2,
             'role': 'decode', 'name': 'engine', 'attempt': 0,
             'start': t0 + 0.5, 'duration_ms': 200.0,
             'status': 'ok',
             'phases': [{'name': 'decode', 'start': t0 + 0.55,
                         'duration_ms': 150.0}]},
            {'request_id': 'r', 'process': 'lb', 'name': 'lb',
             'attempt': 0, 'start': t0, 'duration_ms': 800.0,
             'status': 200,
             'phases': [{'name': 'route', 'start': t0,
                         'duration_ms': 1.0}]},
            {'request_id': 'r', 'process': 'replica', 'replica_id': 1,
             'role': 'prefill', 'name': 'prefill_export',
             'attempt': 0, 'start': t0 + 0.1, 'duration_ms': 120.0,
             'phases': []},
        ]

    def test_causal_order(self):
        ordered = traces_lib.assemble(self._segments())
        assert [s['name'] for s in ordered] == \
            ['lb', 'prefill_export', 'engine']
        # Ties at the same start put the LB first.
        tie = traces_lib.assemble([
            {'process': 'replica', 'start': 5.0, 'name': 'engine'},
            {'process': 'lb', 'start': 5.0, 'name': 'lb'}])
        assert [s['name'] for s in tie] == ['lb', 'engine']

    def test_waterfall_renders_all_processes(self):
        lines = traces_lib.format_waterfall(
            traces_lib.assemble(self._segments()))
        text = '\n'.join(lines)
        assert 'lb' in text
        assert 'replica 1 (prefill)' in text
        assert 'replica 2 (decode)' in text
        assert 'prefill_export' in text
        assert 'route' in text
        # Bars render and every line carries one.
        assert all('|' in line for line in lines)
        assert traces_lib.format_waterfall([]) == ['(no segments)']

    def test_chrome_trace_export(self, tmp_path):
        segments = self._segments()
        events = traces_lib.to_chrome_trace(segments)
        x_events = [e for e in events if e['ph'] == 'X']
        meta = [e for e in events if e['ph'] == 'M']
        # One pid per process, named via metadata events.
        assert {e['args']['name'] for e in meta} == \
            {'lb', 'replica 1 (prefill)', 'replica 2 (decode)'}
        assert len({e['pid'] for e in meta}) == 3
        # Segments + phases all land as complete events with ts/dur.
        assert len(x_events) == 3 + 2
        assert all(e['dur'] >= 0 and e['ts'] > 0 for e in x_events)
        path = tmp_path / 'trace.json'
        traces_lib.export_chrome_trace(segments, str(path))
        payload = json.loads(path.read_text())
        assert len(payload['traceEvents']) == len(events)


class TestConstLabels:

    def test_every_series_carries_process_identity(self):
        registry = metrics_lib.Registry()
        registry.counter('c_total', 'c').inc()
        registry.gauge('g', 'g', ('shard',)).labels(shard='0').set(2)
        registry.histogram('h', 'h', buckets=(1.0,)).observe(0.5)
        registry.set_const_labels({'replica_id': 7, 'role': 'decode',
                                  'num_hosts': 2})
        text = registry.expose()
        parsed = metrics_lib.parse_exposition(text)
        for name in ('c_total', 'g', 'h_bucket', 'h_sum', 'h_count'):
            for labels in parsed[name]:
                ldict = dict(labels)
                assert ldict['replica_id'] == '7', (name, labels)
                assert ldict['role'] == 'decode'
                assert ldict['num_hosts'] == '2'
        # Instrument's own labels still present alongside.
        [labels] = list(parsed['g'])
        assert dict(labels)['shard'] == '0'
        # clear() resets identity (test isolation contract).
        registry.clear()
        assert registry.const_labels() == {}

    def test_histogram_quantile_interpolates(self):
        parsed = {'h_bucket': {
            (('le', '0.1'),): 50.0,
            (('le', '0.2'),): 100.0,
            (('le', '+Inf'),): 100.0}}
        assert metrics_lib.histogram_quantile(parsed, 'h', 0.5) == 0.1
        # Linear interpolation INSIDE the winning bucket.
        assert abs(metrics_lib.histogram_quantile(parsed, 'h', 0.75)
                   - 0.15) < 1e-9
        # First bucket interpolates from 0.
        assert abs(metrics_lib.histogram_quantile(parsed, 'h', 0.25)
                   - 0.05) < 1e-9
        # +Inf clamps to the highest finite bound.
        overflow = {'h_bucket': {(('le', '0.1'),): 0.0,
                                 (('le', '+Inf'),): 10.0}}
        assert metrics_lib.histogram_quantile(overflow, 'h',
                                              0.99) == 0.1
        assert metrics_lib.histogram_quantile({}, 'h', 0.5) is None
        empty = {'h_bucket': {(('le', '+Inf'),): 0.0}}
        assert metrics_lib.histogram_quantile(empty, 'h', 0.5) is None

    def test_quantile_aggregates_across_label_sets(self):
        # Two replicas' buckets sum before the quantile is read.
        parsed = {'h_bucket': {
            (('le', '0.1'), ('replica_id', '1')): 100.0,
            (('le', '+Inf'), ('replica_id', '1')): 100.0,
            (('le', '0.1'), ('replica_id', '2')): 0.0,
            (('le', '+Inf'), ('replica_id', '2')): 100.0}}
        q = metrics_lib.histogram_quantile(parsed, 'h', 0.5)
        assert q == 0.1
