"""Per-pass unit tests on synthetic fixture packages.

Each checker pass gets true-positive and true-negative snippets, plus
the framework contracts: an inline suppression with a reason silences
a finding, a suppression WITHOUT a reason does not (and is itself a
finding), and the baseline round-trips.  Fixture trees are tiny —
every test parses a handful of lines.
"""
from __future__ import annotations

import json
import pathlib
from typing import Dict, Optional

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes import bare_print
from skypilot_tpu.analysis.passes import chaos_sites
from skypilot_tpu.analysis.passes import concurrency
from skypilot_tpu.analysis.passes import env_knobs
from skypilot_tpu.analysis.passes import facade_surface
from skypilot_tpu.analysis.passes import http_contract
from skypilot_tpu.analysis.passes import journal_events
from skypilot_tpu.analysis.passes import journal_protocol
from skypilot_tpu.analysis.passes import mesh_consistency
from skypilot_tpu.analysis.passes import metrics_catalog
from skypilot_tpu.analysis.passes import tracer_safety


def _pkg(tmp_path, files: Dict[str, str],
         docs: Optional[Dict[str, str]] = None,
         tests: Optional[Dict[str, str]] = None) \
        -> index_lib.PackageIndex:
    root = tmp_path / 'pkg'
    for rel, content in {'__init__.py': '', **files}.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content, encoding='utf-8')
    for rel, content in (docs or {}).items():
        path = tmp_path / 'docs' / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content, encoding='utf-8')
    for rel, content in (tests or {}).items():
        path = tmp_path / 'tests' / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content, encoding='utf-8')
    return index_lib.PackageIndex(root)


def _run(idx, pass_obj, rules=None, baseline=None):
    return core.run_lint(idx, passes=[pass_obj], rules=rules,
                         baseline_path=baseline)


def _rules(result):
    return [f.rule for f in result.findings]


# ------------------------------------------------------- concurrency

_LOCK_CYCLE = '''
import threading


class A:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def fwd(self):
        with self._a:
            with self._b:
                pass

    def rev(self):
        with self._b:
            with self._a:
                pass
'''


def test_concurrency_lock_order_cycle(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': _LOCK_CYCLE})
    result = _run(idx, concurrency.ConcurrencyPass())
    assert _rules(result).count('lock-order') == 2
    assert 'A._a' in result.findings[0].message


def test_concurrency_consistent_order_is_clean(tmp_path):
    clean = _LOCK_CYCLE.replace(
        'with self._b:\n            with self._a:',
        'with self._a:\n            with self._b:')
    idx = _pkg(tmp_path, {'mod.py': clean})
    result = _run(idx, concurrency.ConcurrencyPass())
    assert result.ok, _rules(result)


def test_concurrency_blocking_and_transitive_self_deadlock(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import threading
import time
import requests


class A:
    def __init__(self):
        self._a = threading.Lock()

    def slow(self):
        with self._a:
            time.sleep(1)

    def net(self):
        with self._a:
            requests.post('http://x')

    def reenter(self):
        with self._a:
            self.slow()
'''})
    result = _run(idx, concurrency.ConcurrencyPass())
    rules = _rules(result)
    assert rules.count('blocking-under-lock') >= 3  # sleep, post, call
    # Holding _a while calling slow() (which takes _a) is an
    # unconditional deadlock for a plain Lock.
    assert 'lock-order' in rules


def test_concurrency_rlock_reentry_and_cond_wait_clean(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import threading


class A:
    def __init__(self):
        self._a = threading.RLock()
        self._cond = threading.Condition()

    def inner(self):
        with self._a:
            pass

    def outer(self):
        with self._a:
            self.inner()

    def waiter(self):
        with self._cond:
            self._cond.wait(1.0)
'''})
    result = _run(idx, concurrency.ConcurrencyPass())
    assert result.ok, [f.render() for f in result.findings]


def test_concurrency_unlocked_attr(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import threading


class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def locked(self):
        with self._lock:
            self.count += 1

    def unlocked(self):
        self.count = 0
'''})
    result = _run(idx, concurrency.ConcurrencyPass())
    assert _rules(result) == ['unlocked-attr']
    assert 'A.count' in result.findings[0].message


def test_suppression_with_reason_honored(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import threading
import time

_lock = threading.Lock()


def slow():
    with _lock:
        # skytpu: lint-ok[blocking-under-lock] reason=test fixture
        time.sleep(1)
'''})
    result = _run(idx, concurrency.ConcurrencyPass())
    assert result.ok
    assert [f.rule for f in result.suppressed] == \
        ['blocking-under-lock']


def test_suppression_without_reason_rejected(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import threading
import time

_lock = threading.Lock()


def slow():
    with _lock:
        time.sleep(1)  # skytpu: lint-ok[blocking-under-lock]
'''})
    result = _run(idx, concurrency.ConcurrencyPass())
    rules = set(_rules(result))
    # The finding stands AND the reasonless suppression is flagged.
    assert rules == {'blocking-under-lock',
                     core.RULE_SUPPRESSION_INVALID}
    assert not result.suppressed


# ----------------------------------------------------- tracer safety

def test_tracer_branch_item_and_clock_flagged(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import time

import jax
import jax.numpy as jnp


def step(state, tokens):
    t = time.time()
    if tokens > 0:
        state = state + 1
    n = int(tokens.sum().item())
    return state, t, n


step_jit = jax.jit(step)
'''})
    result = _run(idx, tracer_safety.TracerSafetyPass())
    messages = ' / '.join(f.message for f in result.findings)
    assert 'wall-clock' in messages
    assert 'Python branch' in messages
    assert '.item()' in messages


def test_tracer_static_shapes_and_none_checks_clean(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import jax


def step(state, mask, cfg=None):
    if state.shape[0] > 4:
        pass
    if mask is None:
        return state
    return state * 2


step_jit = jax.jit(step, static_argnames=('cfg',))
'''})
    result = _run(idx, tracer_safety.TracerSafetyPass())
    assert result.ok, [f.render() for f in result.findings]


def test_tracer_reachability_through_callee(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import time

import jax


def helper(x):
    time.time()
    return x


def entry(x):
    return helper(x)


entry_jit = jax.jit(entry)
'''})
    result = _run(idx, tracer_safety.TracerSafetyPass())
    assert _rules(result) == ['tracer-safety']
    assert result.findings[0].line == 8  # the time.time() in helper


def test_tracer_partial_bound_params_static(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import functools

import jax


def step(cfg, tokens):
    if cfg.debug:
        pass
    return tokens


step_jit = jax.jit(functools.partial(step, object()))
'''})
    result = _run(idx, tracer_safety.TracerSafetyPass())
    assert result.ok, [f.render() for f in result.findings]


# --------------------------------------------------------- env knobs

_ENV_DOC = '''# Env vars

| variable | meaning |
|---|---|
| `SKYTPU_FOO` | documented and read |
| `SKYTPU_BAZ` | read only by the test harness |
| `SKYTPU_GONE` | documented but dead |
'''


def test_env_knobs_both_directions(tmp_path):
    idx = _pkg(
        tmp_path,
        {'mod.py': '''
import os

FOO = os.environ.get('SKYTPU_FOO')
BAR = os.environ.get('SKYTPU_BAR')
'''},
        docs={'environment-variables.md': _ENV_DOC},
        tests={'test_x.py': "import os; os.environ['SKYTPU_BAZ']"})
    result = _run(idx, env_knobs.EnvKnobsPass())
    by_rule = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert list(by_rule.get('env-undocumented', [])) and \
        'SKYTPU_BAR' in by_rule['env-undocumented'][0]
    # SKYTPU_BAZ is harness-referenced -> not stale; SKYTPU_GONE is.
    stale = ' '.join(by_rule.get('env-stale-doc', []))
    assert 'SKYTPU_GONE' in stale
    assert 'SKYTPU_BAZ' not in stale


# ---------------------------------------------------- journal events

_JOURNAL_DOC = '''# Obs

### Journal event vocabulary

| event | journal | fields |
|---|---|---|
| `good_event` | t | documented |
| `span_start` `span_end` | t | via ControlSpan |
| `ghost_event` | t | documented but never emitted |
'''


def test_journal_events_both_directions(tmp_path):
    idx = _pkg(
        tmp_path,
        {'mod.py': '''
from pkg import events_lib


def _journal_it(event, **fields):
    events_lib.get_journal().append(event, **fields)


def work(journal, name):
    _journal_it('good_event', x=1)
    _journal_it('rogue_event')
    events_lib.ControlSpan(journal, 'span')
    journal.append(name, y=2)
''',
         'events_lib.py': '''
def get_journal():
    raise NotImplementedError


class ControlSpan:
    def __init__(self, journal, name):
        self._journal = journal
        self._name = name
'''},
        docs={'observability.md': _JOURNAL_DOC})
    result = _run(idx, journal_events.JournalEventsPass())
    by_rule = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert 'rogue_event' in ' '.join(by_rule['journal-undocumented'])
    assert 'good_event' not in ' '.join(
        by_rule['journal-undocumented'])
    assert 'ghost_event' in ' '.join(by_rule['journal-stale-doc'])
    # journal.append(name, ...) with a non-literal name is flagged.
    assert by_rule.get('journal-computed-name')


# --------------------------------------------------- metrics catalog

def test_metrics_catalog_both_directions(tmp_path):
    doc = '''# Obs

| series | type |
|---|---|
| `skytpu_documented_total` | counter |
| `skytpu_ghost_total` | counter |
'''
    idx = _pkg(
        tmp_path,
        {'mod.py': '''
from pkg import m

A = m.counter('skytpu_documented_total', 'x')
B = m.counter('skytpu_rogue_total', 'x')
'''},
        docs={'observability.md': doc})
    result = _run(idx, metrics_catalog.MetricsCatalogPass())
    rules = _rules(result)
    assert rules == ['metrics-undocumented', 'metrics-stale-doc'] or \
        sorted(rules) == ['metrics-stale-doc', 'metrics-undocumented']
    messages = ' '.join(f.message for f in result.findings)
    assert 'skytpu_rogue_total' in messages
    assert 'skytpu_ghost_total' in messages


# ------------------------------------------------------- chaos sites

def test_chaos_sites_helpers(tmp_path):
    idx = _pkg(tmp_path, {
        'chaos/__init__.py': '',
        'chaos/faults.py': "SITES = {'a.b': 'doc', 'c.d': 'doc'}\n",
        'mod.py': '''
def work(inject, name):
    inject('a.b')
    inject('x.y')
    inject(name)
''',
    })
    registered = chaos_sites.registered_sites(idx)
    assert registered == ['a.b', 'c.d']
    sites, non_literal = chaos_sites.inject_call_sites(idx)
    assert set(sites) == {'a.b', 'x.y'}
    assert non_literal == [('mod.py', 5)]
    findings = list(chaos_sites.ChaosSitesPass().run(idx))
    rules = sorted({f.rule for f in findings})
    assert 'chaos-site-unregistered' in rules   # x.y
    assert 'chaos-site-computed' in rules       # inject(name)
    assert 'chaos-site-stale' in rules          # c.d never injected


# ---------------------------------------------------- facade surface

def test_facade_missing_and_stale(tmp_path):
    idx = _pkg(tmp_path, {
        'serve/__init__.py': '',
        'serve/scheduler.py': 'class Request:\n    pass\nLIMIT = 3\n',
        'serve/cache_manager.py': 'class PagePool:\n    pass\n',
        'serve/sampler.py': 'def validate_sampling():\n    pass\n',
        'serve/batching_engine.py': '''
from pkg.serve import cache_manager
from pkg.serve import sampler as sampler_lib
from pkg.serve import scheduler

Request = scheduler.Request
PagePool = cache_manager.PagePool
validate_sampling = sampler_lib.validate_sampling
Ghost = scheduler.LongGone
''',
    })
    findings = list(facade_surface.FacadeSurfacePass().run(idx))
    missing = [f.message for f in findings
               if f.rule == 'facade-missing']
    stale = [f.message for f in findings if f.rule == 'facade-stale']
    assert any('LIMIT' in m for m in missing)
    assert len(missing) == 1
    assert len(stale) == 1 and 'LongGone' in stale[0]


# -------------------------------------------------------- bare print

def test_bare_print_flagged_outside_allowlist(tmp_path):
    idx = _pkg(tmp_path, {
        'mod.py': "print('no')\n",
        'cli.py': "print('stdout is the product here')\n",
    })
    findings = list(bare_print.BarePrintPass().run(idx))
    flagged = [f for f in findings if f.rule == 'bare-print']
    assert [f.file for f in flagged] == ['mod.py']


# ----------------------------------------------- baseline round-trip

def test_baseline_grandfathers_then_goes_stale(tmp_path):
    files = {'mod.py': "print('x')\n"}
    idx = _pkg(tmp_path, files)
    pass_obj = bare_print.BarePrintPass()
    first = _run(idx, pass_obj)
    flagged = [f for f in first.findings if f.rule == 'bare-print']
    assert flagged
    baseline = tmp_path / core.BASELINE_FILENAME
    core.write_baseline(baseline, flagged)

    # Grandfathered: same tree is now clean (modulo the allowlist
    # staleness this fixture package inherently has).
    second = _run(idx, pass_obj, rules=['bare-print'],
                  baseline=baseline)
    assert second.ok, [f.render() for f in second.findings]
    assert [f.rule for f in second.baselined] == ['bare-print']

    # The print is fixed -> the baseline entry is stale -> finding.
    fixed = _pkg(tmp_path / 'v2', {'mod.py': 'x = 1\n'})
    third = _run(fixed, pass_obj, rules=['bare-print'],
                 baseline=baseline)
    assert core.RULE_BASELINE_STALE in _rules(third)
    assert not third.ok


def test_baseline_stale_scoped_to_ran_rules(tmp_path):
    """A --rule filter must not declare other rules' baseline entries
    stale: their passes did not run, so absence proves nothing."""
    idx = _pkg(tmp_path, {'mod.py': 'x = 1\n'})
    baseline = tmp_path / core.BASELINE_FILENAME
    baseline.write_text(json.dumps(
        {'version': 1, 'findings': ['lock-order//mod.py//gone']}))
    passes = [bare_print.BarePrintPass(),
              concurrency.ConcurrencyPass()]
    filtered = core.run_lint(idx, passes=passes,
                             rules=['bare-print'],
                             baseline_path=baseline)
    assert core.RULE_BASELINE_STALE not in _rules(filtered)
    full = core.run_lint(idx, passes=passes, baseline_path=baseline)
    assert core.RULE_BASELINE_STALE in _rules(full)


def test_fixture_json_deterministic(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': _LOCK_CYCLE})
    a = _run(idx, concurrency.ConcurrencyPass()).to_json()
    b = _run(idx, concurrency.ConcurrencyPass()).to_json()
    assert a == b
    assert json.loads(a)['findings']


# ----------------------------------------------------- http contract

_HTTP_PROTOCOL = '''
REQUEST_ID_HEADER = 'X-SkyTPU-Request-Id'
DEADLINE_HEADER = 'X-SkyTPU-Deadline-Ms'
GENERATE = '/generate'
DRAIN = '/drain'
LB_RETIRE = '/lb/retire'
CONTROLLER_SYNC = '/controller/load_balancer_sync'
'''

_HTTP_DOC = '''# Serving

### HTTP API

| route | method |
|---|---|
| `/generate` | POST |
| `/drain` | POST |
| `/lb/retire` | POST |
| `/controller/load_balancer_sync` | POST |
'''


def _http_pkg(tmp_path, threaded, asyncf, lb='', controller='',
              extra=None, doc=_HTTP_DOC):
    files = {
        'serve/__init__.py': '',
        'serve/http_protocol.py': _HTTP_PROTOCOL,
        'serve/model_server.py': threaded,
        'serve/async_server.py': asyncf,
        'serve/load_balancer.py': lb,
        'serve/controller.py': controller,
    }
    files.update(extra or {})
    return _pkg(tmp_path, files, docs={'serving.md': doc})


_FRONT = '''
from pkg.serve import http_protocol


def handle(self, path):
    if path == http_protocol.GENERATE:
        rid = self.headers.get(http_protocol.REQUEST_ID_HEADER)
        self._reply(200, {'rid': rid})
    elif path == http_protocol.DRAIN:
        self._reply(200, {})
    else:
        self._reply(404, {})
'''

_LB = '''
import requests

from pkg.serve import http_protocol


def control(self, method, path):
    if method == 'POST' and path == http_protocol.LB_RETIRE:
        self._reply(200, {})


def sync(self, url):
    resp = requests.post(url + http_protocol.CONTROLLER_SYNC, json={})
    if resp.status_code == 200:
        return resp.json()
    return None


def stamp(self, extra):
    extra[http_protocol.REQUEST_ID_HEADER] = 'rid'
    extra[http_protocol.DEADLINE_HEADER] = '100'
'''

_CONTROLLER = '''
from pkg.serve import http_protocol


def handle(self, path):
    if self.path == http_protocol.CONTROLLER_SYNC:
        self._json(200, {})
    deadline = self.headers.get(http_protocol.DEADLINE_HEADER)
    return deadline
'''


def test_http_contract_clean_fixture(tmp_path):
    idx = _http_pkg(tmp_path, _FRONT, _FRONT, _LB, _CONTROLLER)
    result = _run(idx, http_contract.HttpContractPass())
    assert result.ok, [f.render() for f in result.findings]


def test_http_contract_front_parity_drift(tmp_path):
    # The async front forgets /drain AND stops reading the request id.
    async_front = '''
from pkg.serve import http_protocol


def handle(self, path):
    if path == http_protocol.GENERATE:
        self._reply(200, {})
    else:
        self._reply(404, {})
'''
    idx = _http_pkg(tmp_path, _FRONT, async_front, _LB, _CONTROLLER)
    result = _run(idx, http_contract.HttpContractPass())
    parity = [f.message for f in result.findings
              if f.rule == 'http-front-parity']
    assert any("'/drain'" in m and 'threaded front only' in m
               for m in parity)
    assert any('X-SkyTPU-Request-Id' in m for m in parity)


def test_http_contract_unknown_route_and_status(tmp_path):
    lb = _LB + '''

def probe(url):
    resp = requests.post(url + '/nope', json={})
    if resp.status_code == 418:
        return True
    return False
'''
    idx = _http_pkg(tmp_path, _FRONT, _FRONT, lb, _CONTROLLER)
    result = _run(idx, http_contract.HttpContractPass())
    rules = _rules(result)
    assert 'http-unknown-route' in rules
    assert 'http-status-unemittable' in rules
    # '/nope' is also a raw path literal?  No: only canonical values
    # are banned; unknown paths surface through http-unknown-route.
    messages = ' '.join(f.message for f in result.findings)
    assert "'/nope'" in messages
    assert '418' in messages


def test_http_contract_raw_literal_and_unstamped(tmp_path):
    front = _FRONT + '''

def rogue(self):
    token = self.headers.get('X-SkyTPU-Secret-Token')
    raw = '/generate'
    return token, raw
'''
    idx = _http_pkg(tmp_path, front, _FRONT, _LB, _CONTROLLER)
    result = _run(idx, http_contract.HttpContractPass())
    rules = _rules(result)
    assert 'http-raw-literal' in rules       # the raw '/generate'
    assert 'http-header-unstamped' in rules  # nothing stamps the token
    messages = ' '.join(f.message for f in result.findings)
    assert 'X-SkyTPU-Secret-Token' in messages


def test_http_contract_header_unread_and_doc_drift(tmp_path):
    # DEADLINE_HEADER defined but no server reads it; docs list a
    # ghost route and miss /drain.
    controller = '''
from pkg.serve import http_protocol


def handle(self):
    if self.path == http_protocol.CONTROLLER_SYNC:
        self._json(200, {})
'''
    doc = _HTTP_DOC.replace('| `/drain` | POST |\n', '') + \
        '| `/ghost` | GET |\n'
    idx = _http_pkg(tmp_path, _FRONT, _FRONT, _LB, controller,
                    doc=doc)
    result = _run(idx, http_contract.HttpContractPass())
    by_rule = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    assert any('X-SkyTPU-Deadline-Ms' in m
               for m in by_rule.get('http-header-unread', []))
    drift = ' '.join(by_rule.get('http-doc-drift', []))
    assert "'/drain'" in drift and "'/ghost'" in drift


# --------------------------------------------------- journal protocol

_EVENT_PROTOCOL = '''
SCOPE_INVOCATION = 'invocation'
SCOPE_PROCESS = 'process'


def _pair(name, scope, start=None, end=None, status_field=None,
          statuses=None):
    raise NotImplementedError


PAIRS = (
    _pair('work', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'fail')),
    _pair('drain', SCOPE_PROCESS),
)
'''


_WORK_ONLY_PROTOCOL = _EVENT_PROTOCOL.replace(
    "    _pair('drain', SCOPE_PROCESS),\n", '')


def _journal_pkg(tmp_path, mod, protocol=_EVENT_PROTOCOL):
    return _pkg(tmp_path, {
        'observability/__init__.py': '',
        'observability/event_protocol.py': protocol,
        'mod.py': mod,
    })


def test_journal_protocol_clean_guarded(tmp_path):
    idx = _journal_pkg(tmp_path, '''
def run(journal, ok):
    journal.append('work_start', n=1)
    try:
        do_work()
    finally:
        journal.append('work_end', status='ok' if ok else 'fail')


def open_drain(journal):
    journal.append('drain_start')


def close_drain(journal):
    journal.append('drain_end')
''')
    result = _run(idx, journal_protocol.JournalProtocolPass())
    assert result.ok, [f.render() for f in result.findings]


def test_journal_protocol_unguarded_start(tmp_path):
    idx = _journal_pkg(tmp_path, '''
def run(journal):
    journal.append('work_start', n=1)
    do_work()
    journal.append('work_end', status='ok')
''', protocol=_WORK_ONLY_PROTOCOL)
    result = _run(idx, journal_protocol.JournalProtocolPass())
    assert _rules(result) == ['journal-unguarded-start']


def test_journal_protocol_unregistered_and_stale(tmp_path):
    protocol = _EVENT_PROTOCOL.replace(
        "    _pair('drain', SCOPE_PROCESS),\n",
        "    _pair('drain', SCOPE_PROCESS),\n"
        "    _pair('ghost', SCOPE_PROCESS),\n")
    idx = _journal_pkg(tmp_path, '''
def run(journal):
    journal.append('rogue_start')
''', protocol=protocol)
    result = _run(idx, journal_protocol.JournalProtocolPass())
    rules = set(_rules(result))
    assert 'journal-protocol-unregistered' in rules   # rogue_start
    assert 'journal-protocol-stale' in rules          # ghost + drain
    messages = ' '.join(f.message for f in result.findings)
    assert 'rogue_start' in messages and 'ghost' in messages


def test_journal_protocol_bad_status(tmp_path):
    idx = _journal_pkg(tmp_path, '''
def run(journal):
    journal.append('work_start')
    try:
        do_work()
    finally:
        journal.append('work_end', status='oops')
''', protocol=_WORK_ONLY_PROTOCOL)
    result = _run(idx, journal_protocol.JournalProtocolPass())
    assert _rules(result) == ['journal-protocol-status']
    assert "'oops'" in result.findings[0].message


def test_journal_protocol_wrapper_and_except_guard(tmp_path):
    # Wrapper-mediated emits count; an except-handler end guards too.
    idx = _journal_pkg(tmp_path, '''
def _emit(event, **fields):
    get_journal().append(event, **fields)


def run(journal):
    _emit('work_start')
    try:
        do_work()
    except Exception:
        _emit('work_end', status='fail')
        raise
    _emit('work_end', status='ok')
''', protocol=_WORK_ONLY_PROTOCOL)
    result = _run(idx, journal_protocol.JournalProtocolPass())
    assert result.ok, [f.render() for f in result.findings]


# --------------------------------------------------- mesh consistency

def test_mesh_unknown_axis_flagged(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import jax
import numpy as np

P = jax.sharding.PartitionSpec

mesh = jax.sharding.Mesh(np.array(jax.devices()), ('data', 'tensor'))
good = jax.sharding.NamedSharding(mesh, P(None, 'tensor'))
bad = jax.sharding.NamedSharding(mesh, P(None, 'tensr'))
'''})
    result = _run(idx, mesh_consistency.MeshConsistencyPass())
    assert _rules(result) == ['mesh-unknown-axis']
    assert "'tensr'" in result.findings[0].message


def test_mesh_axes_resolved_through_constants(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import jax

DCN = ('data',)
ICI = ('fsdp', 'tensor')


def build(devices):
    axis_names = list(DCN + ICI)
    return jax.sharding.Mesh(devices, axis_names)


P = jax.sharding.PartitionSpec
spec = P('data', ('fsdp', 'tensor'))
'''})
    result = _run(idx, mesh_consistency.MeshConsistencyPass())
    assert result.ok, [f.render() for f in result.findings]


def test_mesh_donated_reuse_flagged(tmp_path):
    idx = _pkg(tmp_path, {'mod.py': '''
import jax


def step(state):
    return state


step_jit = jax.jit(step, donate_argnums=(0,))


def bad(state):
    out = step_jit(state)
    return state.loss, out


def good(state):
    state = step_jit(state)
    return state.loss
'''})
    result = _run(idx, mesh_consistency.MeshConsistencyPass())
    assert _rules(result) == ['mesh-donated-reuse']
    assert result.findings[0].file == 'mod.py'
