"""GKE TPU node-pool provisioner tests against a faked gcloud/kubectl."""
from __future__ import annotations

import json
import subprocess
from typing import Dict, List

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common as pcommon
from skypilot_tpu.provision.gke import instance as gke
from skypilot_tpu.status_lib import ClusterStatus


class FakeGkeCli:
    """Emulates gcloud node pools + kubectl pods in memory."""

    def __init__(self):
        self.pools: Dict[str, dict] = {}
        self.pods: Dict[str, dict] = {}
        self.services: Dict[str, dict] = {}
        self.commands: List[List[str]] = []

    def __call__(self, argv, stdin=None):
        self.commands.append(argv)
        if argv[:3] == ['gcloud', 'container', 'node-pools']:
            return self._pools(argv)
        if argv[:4] == ['gcloud', 'container', 'clusters',
                        'get-credentials']:
            return self._done()
        if argv[:3] == ['kubectl', 'config', 'current-context']:
            return self._done(0, 'gke_test-proj_us-central2-b_my-gke\n')
        if argv[0] == 'kubectl':
            return self._kubectl(argv, stdin)
        raise AssertionError(f'unhandled {argv}')

    @staticmethod
    def _done(rc=0, stdout='', stderr=''):
        return subprocess.CompletedProcess([], rc, stdout=stdout,
                                           stderr=stderr)

    def _pools(self, argv):
        verb, name = argv[3], argv[4]
        if verb == 'describe':
            if name in self.pools:
                return self._done(0, json.dumps(self.pools[name]))
            return self._done(1, stderr='NotFound')
        if verb == 'create':
            self.pools[name] = {'argv': argv}
            return self._done()
        if verb == 'delete':
            if name not in self.pools:
                return self._done(1, stderr='NotFound')
            del self.pools[name]
            return self._done()
        raise AssertionError(argv)

    def _kubectl(self, argv, stdin):
        args = argv[argv.index('-n') + 2:]  # skip kubectl [--context c] -n ns
        if args[0] == 'apply':
            obj = json.loads(stdin)
            if obj['kind'] == 'Pod':
                name = obj['metadata']['name']
                obj['status'] = {'phase': 'Running',
                                 'podIP': f'10.8.0.{len(self.pods) + 1}'}
                self.pods[name] = obj
            else:
                self.services[obj['metadata']['name']] = obj
            return self._done()
        if args[0] == 'get' and args[1] == 'pod':
            name = args[2]
            if name in self.pods:
                if '-o' in args and args[args.index('-o') + 1] == 'json':
                    return self._done(0, json.dumps(self.pods[name]))
                return self._done(0, f'pod/{name}')
            return self._done(1, stderr='not found')
        if args[0] == 'get' and args[1] == 'pods':
            label = args[args.index('-l') + 1]
            cluster = label.split('=')[1]
            items = [p for p in self.pods.values()
                     if p['metadata']['labels'].get('skytpu-cluster') ==
                     cluster]
            return self._done(0, json.dumps({'items': items}))
        if args[0] == 'delete' and args[1] == 'pod':
            self.pods.pop(args[2], None)
            return self._done()
        if args[0] == 'delete' and args[1] == 'pods':
            label = args[args.index('-l') + 1]
            cluster = label.split('=')[1]
            self.pods = {
                n: p for n, p in self.pods.items()
                if p['metadata']['labels'].get('skytpu-cluster') != cluster}
            return self._done()
        if args[0] == 'delete' and args[1] == 'service':
            self.services.pop(args[2], None)
            return self._done()
        raise AssertionError(argv)


@pytest.fixture()
def fake_cli(monkeypatch):
    cli = FakeGkeCli()
    monkeypatch.setattr(gke, '_run_cli', cli)
    yield cli


def _config(cluster='gk1', hosts=2, chips=8, spot=False):
    return pcommon.ProvisionConfig(
        provider_name='gke', cluster_name=cluster, region='us-central2',
        zones=['us-central2-b'],
        deploy_vars={
            'tpu': True,
            'tpu_accelerator_type': 'v5litepod-8',
            'tpu_topology': '2x4',
            'tpu_num_hosts': hosts,
            'tpu_num_chips': chips,
            'use_spot': spot,
            'gke_cluster': 'my-gke',
            'gke_location': 'us-central2-b',
            'gke_machine_type': 'ct5lp-hightpu-4t',
            'gke_namespace': 'default',
        })


class TestGke:

    def test_create_pool_and_pods(self, fake_cli):
        record = gke.run_instances(_config())
        assert record.created_instance_ids == ['gk1-host0', 'gk1-host1']
        assert 'skytpu-gk1' in fake_cli.pools
        create = fake_cli.pools['skytpu-gk1']['argv']
        assert '--tpu-topology' in create
        assert '--machine-type' in create
        pod = fake_cli.pods['gk1-host0']
        assert pod['spec']['containers'][0]['resources']['requests'][
            'google.com/tpu'] == '4'
        assert pod['spec']['nodeSelector'][
            'cloud.google.com/gke-nodepool'] == 'skytpu-gk1'

        gke.wait_instances('gk1')
        info = gke.get_cluster_info('gk1')
        assert info.num_hosts == 2
        assert [i.worker_id for i in info.instances] == [0, 1]
        runners = gke.get_command_runners(info)
        assert runners[0].pod_name == 'gk1-host0'

    def test_idempotent(self, fake_cli):
        gke.run_instances(_config())
        record = gke.run_instances(_config())
        assert record.created_instance_ids == []
        assert record.resumed_instance_ids == ['gk1-host0', 'gk1-host1']

    def test_spot_flag(self, fake_cli):
        gke.run_instances(_config(spot=True))
        assert '--spot' in fake_cli.pools['skytpu-gk1']['argv']

    def test_query_and_terminate(self, fake_cli):
        gke.run_instances(_config())
        statuses = gke.query_instances('gk1')
        assert statuses == {'gk1-host0': ClusterStatus.UP,
                            'gk1-host1': ClusterStatus.UP}
        gke.terminate_instances('gk1')
        assert fake_cli.pools == {}
        assert fake_cli.pods == {}
        assert gke.query_instances('gk1') == {}

    def test_stop_rejected(self, fake_cli):
        gke.run_instances(_config())
        with pytest.raises(exceptions.NotSupportedError):
            gke.stop_instances('gk1')

    def test_open_cleanup_ports(self, fake_cli):
        gke.run_instances(_config())
        gke.open_ports('gk1', [8080, 9000])
        svc = fake_cli.services['gk1-svc']
        assert {p['port'] for p in svc['spec']['ports']} == {8080, 9000}
        gke.cleanup_ports('gk1')
        assert fake_cli.services == {}

    def test_missing_gke_cluster_config(self, fake_cli):
        config = _config()
        config.deploy_vars['gke_cluster'] = None
        with pytest.raises(exceptions.ProvisionError):
            gke.run_instances(config)

    def test_kubectl_pinned_to_cluster_context(self, fake_cli):
        gke.run_instances(_config())
        kubectl_cmds = [c for c in fake_cli.commands
                        if c[0] == 'kubectl' and '--context' in c]
        assert kubectl_cmds, 'kubectl calls must pin --context'
        ctx = kubectl_cmds[0][kubectl_cmds[0].index('--context') + 1]
        assert 'my-gke' in ctx

    def test_query_raises_on_kubectl_failure(self, fake_cli,
                                             monkeypatch):
        gke.run_instances(_config())

        def broken(argv, stdin=None):
            if argv[0] == 'kubectl' and 'get' in argv:
                import subprocess as sp
                return sp.CompletedProcess(argv, 1, stdout='',
                                           stderr='connection refused')
            return fake_cli(argv, stdin)

        monkeypatch.setattr(gke, '_run_cli', broken)
        with pytest.raises(exceptions.ClusterStatusFetchingError):
            gke.query_instances('gk1')

    def test_wait_fails_fast_on_terminal_pod(self, fake_cli):
        gke.run_instances(_config())
        fake_cli.pods['gk1-host1']['status']['phase'] = 'Failed'
        with pytest.raises(exceptions.ProvisionError,
                           match='terminal'):
            gke.wait_instances('gk1')


class TestGkeCloud:

    def test_registry_and_deploy_vars(self, monkeypatch, _isolated_home):
        from skypilot_tpu import Resources
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.clouds import registry
        cfg_path = _isolated_home / 'config.yaml'
        cfg_path.write_text('gke:\n  cluster: my-gke\n'
                            '  location: us-central2-b\n')
        monkeypatch.setenv('SKYTPU_CONFIG', str(cfg_path))
        config_lib.reload_config()
        cloud = registry.from_str('gke')
        resources = Resources(cloud='gke', accelerators='tpu-v5e-8')
        launchable, _ = cloud.get_feasible_launchable_resources(resources)
        assert launchable
        region = cloud.regions_with_offering(resources)[0]
        deploy = cloud.make_deploy_resources_variables(
            resources, 'c1', region, region.zones)
        assert deploy['gke_cluster'] == 'my-gke'
        assert deploy['gke_machine_type'] == 'ct5lp-hightpu-8t'
        config_lib.reload_config()
