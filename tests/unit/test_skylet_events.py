"""Skylet reconciliation events: orphaned controllers are detected.

VERDICT round-1 item 7 (parity: /root/reference/sky/skylet/events.py:70-88
ManagedJobUpdateEvent / ServiceUpdateEvent): a managed job or service
whose controller process died must not show RUNNING/READY forever.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.serve import serve_state
from skypilot_tpu.skylet import events


def _spawn_victim() -> subprocess.Popen:
    return subprocess.Popen([sys.executable, '-c',
                             'import time; time.sleep(600)'])


def _run(event: events.SkyletEvent) -> None:
    event._last_run_at = 0.0  # pylint: disable=protected-access
    event.maybe_run()


def _submit(job_id: int, pid: int, status=jobs_state.ManagedJobStatus.RUNNING):
    jobs_state.allocate_job_id(f'job{job_id}')
    jobs_state.submit_job(job_id, f'job{job_id}', '/tmp/dag.yaml',
                          task_names=['t'])
    jobs_state.set_status(job_id, 0, status)
    jobs_state.set_controller_pid(job_id, pid)


class TestManagedJobUpdateEvent:

    def test_dead_controller_marks_failed_controller(self):
        victim = _spawn_victim()
        _submit(1, victim.pid)
        victim.kill()
        victim.wait()
        _run(events.ManagedJobUpdateEvent())
        assert jobs_state.get_status(1) == \
            jobs_state.ManagedJobStatus.FAILED_CONTROLLER
        reason = jobs_state.get_job_records(1)[0]['failure_reason']
        assert 'died' in reason

    def test_live_controller_untouched(self):
        victim = _spawn_victim()
        try:
            _submit(2, victim.pid)
            _run(events.ManagedJobUpdateEvent())
            assert jobs_state.get_status(2) == \
                jobs_state.ManagedJobStatus.RUNNING
        finally:
            victim.kill()
            victim.wait()

    def test_terminal_job_untouched(self):
        _submit(3, 999999999,
                status=jobs_state.ManagedJobStatus.SUCCEEDED)
        _run(events.ManagedJobUpdateEvent())
        assert jobs_state.get_status(3) == \
            jobs_state.ManagedJobStatus.SUCCEEDED

    def test_unregistered_controller_untouched(self):
        jobs_state.allocate_job_id('job4')
        jobs_state.submit_job(4, 'job4', '/tmp/dag.yaml', task_names=['t'])
        jobs_state.set_status(4, 0, jobs_state.ManagedJobStatus.PENDING)
        _run(events.ManagedJobUpdateEvent())
        assert jobs_state.get_status(4) == \
            jobs_state.ManagedJobStatus.PENDING


class TestServiceUpdateEvent:

    def _add_service(self, name: str, pid: int) -> None:
        serve_state.add_service(name, spec_json={},
                                task_yaml_path='/tmp/task.yaml')
        serve_state.set_service_status(name,
                                       serve_state.ServiceStatus.READY)
        serve_state.set_service_pids(name, controller_pid=pid)
        rid = serve_state.allocate_replica(name, cluster_prefix=f'{name}-r')
        serve_state.set_replica_status(name, rid,
                                       serve_state.ReplicaStatus.READY)

    def test_dead_controller_marks_service_failed(self):
        victim = _spawn_victim()
        self._add_service('svc1', victim.pid)
        victim.kill()
        victim.wait()
        _run(events.ServiceUpdateEvent())
        assert serve_state.get_service('svc1')['status'] == \
            serve_state.ServiceStatus.FAILED.value
        replicas = serve_state.get_replicas('svc1')
        assert all(r['status'] == serve_state.ReplicaStatus.FAILED.value
                   for r in replicas)

    def test_live_controller_untouched(self):
        victim = _spawn_victim()
        try:
            self._add_service('svc2', victim.pid)
            _run(events.ServiceUpdateEvent())
            assert serve_state.get_service('svc2')['status'] == \
                serve_state.ServiceStatus.READY.value
        finally:
            victim.kill()
            victim.wait()

    def test_dead_lb_marks_service_failed(self):
        controller = _spawn_victim()
        lb = _spawn_victim()
        try:
            self._add_service('svc3', controller.pid)
            serve_state.set_service_pids('svc3', lb_pid=lb.pid)
            lb.kill()
            lb.wait()
            _run(events.ServiceUpdateEvent())
            assert serve_state.get_service('svc3')['status'] == \
                serve_state.ServiceStatus.FAILED.value
        finally:
            controller.kill()
            controller.wait()


class _TickEvent(events.SkyletEvent):
    EVENT_INTERVAL_SECONDS = 100

    def __init__(self):
        super().__init__()
        self.calls = 0
        self.fail = False

    def run(self):
        self.calls += 1
        if self.fail:
            raise RuntimeError('boom')


class TestStaggerAndBackoff:
    """ISSUE 4 satellite: `_last_run_at = 0.0` used to fire every event
    on the first tick simultaneously, and a persistently crashing event
    re-fired at full interval forever."""

    def test_initial_runs_staggered(self):
        batch = [_TickEvent() for _ in range(8)]
        due = [e for e in batch
               if time.time() - e._last_run_at >=  # pylint: disable=protected-access
               e.current_interval()]
        # Exactly one of 8 consecutive instances lands on the zero
        # offset; the rest wait out their stagger slot.
        assert len(due) == 1

    def test_failure_backoff_capped_and_reset(self):
        from skypilot_tpu.observability import events as obs_events
        event = _TickEvent()
        event.fail = True
        failures = obs_events.skylet_event_failures().labels(
            event='_TickEvent')
        before = failures.value

        event._last_run_at = 0.0  # pylint: disable=protected-access
        event.maybe_run()
        assert event.calls == 1
        assert failures.value == before + 1
        assert event.current_interval() == 200  # 2x after 1 failure

        # Within the backed-off window: suppressed even though the base
        # interval elapsed.
        event._last_run_at = time.time() - 150  # pylint: disable=protected-access
        event.maybe_run()
        assert event.calls == 1

        # Past the backed-off window: runs again, backoff doubles.
        event._last_run_at = time.time() - 250  # pylint: disable=protected-access
        event.maybe_run()
        assert event.calls == 2
        assert event.current_interval() == 400

        # Cap: never beyond MAX_BACKOFF_MULTIPLIER x interval.
        event._consecutive_failures = 99  # pylint: disable=protected-access
        assert event.current_interval() == \
            100 * events.MAX_BACKOFF_MULTIPLIER

        # A success resets the backoff to the base interval.
        event.fail = False
        event._consecutive_failures = 3  # pylint: disable=protected-access
        event._last_run_at = 0.0  # pylint: disable=protected-access
        event.maybe_run()
        assert event.calls == 3
        assert event.current_interval() == 100

    def test_tick_journaled_with_duration(self):
        from skypilot_tpu.observability import events as obs_events
        event = _TickEvent()
        event._last_run_at = 0.0  # pylint: disable=protected-access
        event.maybe_run()
        ticks = [e for e in obs_events.skylet_journal().read()
                 if e.get('event_name') == '_TickEvent']
        assert ticks, 'tick not journaled'
        assert ticks[-1]['status'] == 'ok'
        assert ticks[-1]['duration_s'] >= 0
        hist = obs_events.skylet_tick_hist().labels(event='_TickEvent')
        assert hist.value >= 1  # histogram count


def test_pid_alive_helper():
    assert events._pid_alive(os.getpid())  # pylint: disable=protected-access
    victim = _spawn_victim()
    assert events._pid_alive(victim.pid)  # pylint: disable=protected-access
    victim.kill()
    victim.wait()
    # Reaped child: zombie or gone, either way not alive.
    time.sleep(0.1)
    assert not events._pid_alive(victim.pid)  # pylint: disable=protected-access
    assert not events._pid_alive(None)  # pylint: disable=protected-access
    assert not events._pid_alive(0)  # pylint: disable=protected-access
