"""Fleet log plane unit tests (ISSUE 19): the structured record ring,
request-identity context binding, the access-log demotion + HTTP
counter, the error-spike tracker's journal alerts, and the CLI fan-in
helpers — plus the handler's ≤3% overhead budget.
"""
from __future__ import annotations

import logging
import threading
import time

import pytest

from skypilot_tpu import cli
from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import invariants
from skypilot_tpu.observability import aggregator as aggregator_lib
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import traces as traces_lib
from skypilot_tpu.serve import http_protocol


def _counter_value(name, **labels):
    parsed = metrics_lib.parse_exposition(metrics_lib.expose())
    key = tuple(sorted((k, str(v)) for k, v in labels.items()))
    return parsed.get(name, {}).get(key, 0.0)


def _rec(i, **over):
    rec = {'ts': 1000.0 + i * 1e-3, 'level': 'INFO', 'levelno': 20,
           'logger': 'unit', 'msg': f'line {i}'}
    rec.update(over)
    return rec


# ------------------------------------------------------------------ ring

class TestRingExport:

    def test_since_is_exact_seq_cursor(self):
        ring = logs_lib.LogRecordRing(maxlen=16)
        for i in range(5):
            ring.add(_rec(i))
        page = ring.export()
        assert [r['msg'] for r in page] == [f'line {i}'
                                           for i in range(5)]
        cursor = page[2]['seq']
        rest = ring.export(since=cursor)
        # Strictly after: the cursor record itself never reappears.
        assert [r['msg'] for r in rest] == ['line 3', 'line 4']
        assert ring.export(since=page[-1]['seq']) == []

    def test_level_is_a_minimum_severity(self):
        ring = logs_lib.LogRecordRing(maxlen=16)
        ring.add(_rec(0, level='DEBUG', levelno=10))
        ring.add(_rec(1, level='INFO', levelno=20))
        ring.add(_rec(2, level='WARNING', levelno=30))
        ring.add(_rec(3, level='ERROR', levelno=40))
        assert len(ring.export(level='WARNING')) == 2
        assert len(ring.export(level='warning')) == 2    # case-blind
        assert len(ring.export(level='30')) == 2         # numeric
        # Unknown level names degrade to no filter, not a 400.
        assert len(ring.export(level='bogus')) == 4

    def test_request_id_grep_and_limit(self):
        ring = logs_lib.LogRecordRing(maxlen=32)
        for i in range(10):
            ring.add(_rec(i, request_id=f'r{i % 2}'))
        mine = ring.export(request_id='r1')
        assert {r['request_id'] for r in mine} == {'r1'}
        assert len(mine) == 5
        # grep is a regex; a broken pattern falls back to substring.
        assert len(ring.export(grep=r'line [0-3]$')) == 4
        assert [r['msg'] for r in ring.export(grep='line 7[')] \
            == []                        # bad regex, substring miss
        assert len(ring.export(grep='line 7')) == 1
        # limit keeps the NEWEST n.
        tail = ring.export(limit=3)
        assert [r['msg'] for r in tail] == ['line 7', 'line 8',
                                            'line 9']

    def test_cap_evicts_oldest(self):
        ring = logs_lib.LogRecordRing(maxlen=4)
        for i in range(10):
            ring.add(_rec(i))
        assert len(ring) == 4
        assert [r['msg'] for r in ring.export()] == [
            'line 6', 'line 7', 'line 8', 'line 9']

    def test_ring_cap_env_knob(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_LOG_RING_RECORDS', '3')
        ring = logs_lib.LogRecordRing()
        for i in range(5):
            ring.add(_rec(i))
        assert len(ring) == 3
        monkeypatch.setenv('SKYTPU_LOG_RING_RECORDS', 'banana')
        assert logs_lib.ring_records() == \
            logs_lib.DEFAULT_RING_RECORDS


class TestParseLogQuery:

    def test_full_query(self):
        got = logs_lib.parse_log_query(
            'since=7&level=WARNING&request_id=r1&grep=foo&limit=5')
        assert got == {'since': 7.0, 'level': 'WARNING',
                       'request_id': 'r1', 'grep': 'foo', 'limit': 5}

    def test_malformed_values_are_dropped_not_400(self):
        assert logs_lib.parse_log_query('since=abc&limit=xyz') == {}
        assert logs_lib.parse_log_query('') == {}
        assert logs_lib.parse_log_query('bogus=1') == {}


# --------------------------------------------------------------- context

class TestContextBinding:

    def test_bind_merges_and_restores(self):
        with logs_lib.bind(request_id='outer', process='replica',
                           replica_id=1):
            assert logs_lib.current_context()['request_id'] == 'outer'
            with logs_lib.bind(request_id='inner', attempt=1):
                ctx = logs_lib.current_context()
                # Inner overrides rid, inherits the rest.
                assert ctx['request_id'] == 'inner'
                assert ctx['attempt'] == 1
                assert ctx['replica_id'] == 1
            assert logs_lib.current_context()['request_id'] == 'outer'
            assert 'attempt' not in logs_lib.current_context()

    def test_wrap_context_carries_into_bare_thread(self):
        seen = {}

        def probe(key):
            seen[key] = logs_lib.current_context().get('request_id')

        with logs_lib.bind(request_id='r-wrapped'):
            wrapped = logs_lib.wrap_context(probe)
        # A bare worker thread resets contextvars — the classic
        # request-id-loss bug wrap_context exists to fix.
        bare = threading.Thread(target=probe, args=('bare',))
        carried = threading.Thread(target=wrapped, args=('wrapped',))
        for t in (bare, carried):
            t.start()
            t.join()
        assert seen['bare'] is None
        assert seen['wrapped'] == 'r-wrapped'

    def test_process_identity_is_the_fallback(self):
        saved = dict(logs_lib._process_identity)
        try:
            logs_lib.set_process_identity('lb')
            assert logs_lib.current_context()['process'] == 'lb'
            with logs_lib.bind(process='replica', replica_id=2):
                assert logs_lib.current_context()['process'] == \
                    'replica'
        finally:
            logs_lib._process_identity.clear()
            logs_lib._process_identity.update(saved)


# --------------------------------------------------------------- handler

class TestStructuredHandler:

    def test_framework_records_land_in_the_ring(self):
        logger = sky_logging.init_logger('fleet_logs_unit')
        ring = logs_lib.reset_ring()
        before = _counter_value(logs_lib.LOG_RECORDS_SERIES,
                                level='INFO')
        with sky_logging.silent():
            with logs_lib.bind(request_id='rid-h', process='replica',
                               replica_id=3, role='decode'):
                logger.info('hello ring')
        [rec] = ring.export(request_id='rid-h')
        assert rec['msg'] == 'hello ring'
        assert rec['level'] == 'INFO' and rec['levelno'] == 20
        assert rec['logger'] == 'skypilot_tpu.fleet_logs_unit'
        assert rec['process'] == 'replica'
        assert rec['replica_id'] == 3 and rec['role'] == 'decode'
        assert rec['ts'] == pytest.approx(time.time(), abs=30)
        assert _counter_value(logs_lib.LOG_RECORDS_SERIES,
                              level='INFO') == before + 1

    def test_debug_records_dropped_at_default_level(self):
        logger = sky_logging.init_logger('fleet_logs_unit')
        ring = logs_lib.reset_ring()
        with sky_logging.silent():
            logger.debug('too quiet')
        assert ring.export() == []


class TestAccessLog:

    def test_probe_routes_demoted_to_debug(self):
        """The satellite: scrape-path access lines are DEBUG, so at
        the default INFO level they never reach the ring — but the
        HTTP counter still counts them."""
        logger = sky_logging.init_logger('fleet_logs_unit')
        ring = logs_lib.reset_ring()
        before = _counter_value('skytpu_http_requests_total',
                                route=http_protocol.METRICS, code=200)
        with sky_logging.silent():
            logs_lib.access_log(logger, 'GET', http_protocol.METRICS,
                                200)
        assert ring.export() == []
        assert _counter_value('skytpu_http_requests_total',
                              route=http_protocol.METRICS,
                              code=200) == before + 1

    def test_generate_routes_stay_at_info(self):
        logger = sky_logging.init_logger('fleet_logs_unit')
        ring = logs_lib.reset_ring()
        before = _counter_value('skytpu_http_requests_total',
                                route=http_protocol.GENERATE, code=500)
        with sky_logging.silent():
            logs_lib.access_log(logger, 'POST',
                                http_protocol.GENERATE, 500)
        [rec] = ring.export()
        assert rec['msg'] == 'POST /generate -> 500'
        assert rec['level'] == 'INFO'
        assert _counter_value('skytpu_http_requests_total',
                              route=http_protocol.GENERATE,
                              code=500) == before + 1

    def test_every_probe_route_is_a_canonical_path(self):
        for route in logs_lib.PROBE_ROUTES:
            assert route == logs_lib.HEALTH_ROUTE or \
                route in http_protocol.PATHS


# ---------------------------------------------------------- spike alerts

def _seed_linear(store, rid, level, t0, t1, slope, step=30.0):
    """Counter samples growing `slope`/s from t0..t1 inclusive."""
    t = t0
    while t <= t1 + 1e-9:
        store.add(logs_lib.LOG_RECORDS_SERIES,
                  {'replica_id': rid, 'level': level}, t,
                  slope * (t - t0))
        t += step


class TestErrorRatesAndSpikes:

    def test_error_rates_sums_bad_levels_per_replica(self):
        store = aggregator_lib.TimeSeriesStore(retention=1e6)
        now = 10000.0
        _seed_linear(store, '0', 'ERROR', now - 60, now, 1.5)
        _seed_linear(store, '0', 'WARNING', now - 60, now, 0.5)
        _seed_linear(store, '1', 'INFO', now - 60, now, 9.0)
        rates = logs_lib.error_rates(store, 60.0, now)
        assert rates['0'] == pytest.approx(2.0)
        # INFO volume never counts toward the error rate.
        assert '1' not in rates

    def test_spike_starts_and_terminates(self, monkeypatch, tmp_path):
        monkeypatch.setenv('SKYTPU_LOG_ERROR_SPIKE_FAST_WINDOW_S',
                           '60')
        monkeypatch.setenv('SKYTPU_LOG_ERROR_SPIKE_SLOW_WINDOW_S',
                           '300')
        monkeypatch.setenv('SKYTPU_LOG_ERROR_SPIKE_THRESHOLD', '1.0')
        journal = events_lib.EventJournal(
            str(tmp_path / 'serve.jsonl'))
        tracker = logs_lib.LogSpikeTracker('svc', journal=journal)
        store = aggregator_lib.TimeSeriesStore(retention=1e6)
        t0 = 20000.0
        # 2 err/s sustained across the whole slow window: above the
        # 1/s threshold in BOTH windows -> spike starts.
        _seed_linear(store, '0', 'ERROR', t0 - 300, t0, 2.0)
        with sky_logging.silent():
            [status] = tracker.evaluate(store, t0)
        assert status['spiking'] is True
        assert status['rate_fast'] == pytest.approx(2.0)
        assert status['since'] == t0
        # Still spiking while only the slow window remembers: recovery
        # needs the FAST window back under, nothing else.
        flat = 2.0 * 300
        for t in (t0 + 30, t0 + 60, t0 + 90, t0 + 120):
            store.add(logs_lib.LOG_RECORDS_SERIES,
                      {'replica_id': '0', 'level': 'ERROR'}, t, flat)
        with sky_logging.silent():
            [status] = tracker.evaluate(store, t0 + 120)
        assert status['spiking'] is False
        assert tracker.status() == [status]

        events = journal.tail()
        names = [e['event'] for e in events]
        assert names == ['log_error_spike_start',
                         'log_error_spike_end']
        start, end = events
        assert start['replica_id'] == '0'
        assert start['rate_fast'] == pytest.approx(2.0)
        assert start['threshold'] == 1.0
        assert end['duration_s'] == pytest.approx(120.0)
        # Gauges reflect the latest evaluation.
        assert _counter_value('skytpu_log_error_spiking',
                              service='svc', replica_id='0') == 0.0
        # The chaos invariant passes on a terminated spike...
        assert invariants.log_spike_terminates(events) == []
        # ...and flags a dangling one.
        assert invariants.log_spike_terminates(events[:1]) != []

    def test_invariant_registered(self):
        assert 'log_spike_terminates' in invariants.CHECKERS


# ------------------------------------------------------------ CLI fan-in

class TestCliLogHelpers:

    def test_merge_dedupes_shared_ring_exports(self):
        a, b, c = (_rec(0, seq=1), _rec(1, seq=2), _rec(2, seq=3))
        merged = cli._merge_log_records([[b, a], [b, c]])
        # One copy of b, ordered by (ts, seq).
        assert [r['msg'] for r in merged] == ['line 0', 'line 1',
                                              'line 2']
        # A persistent `seen` set makes follow-mode polls incremental.
        seen = set()
        assert len(cli._merge_log_records([[a, b]], seen)) == 2
        assert cli._merge_log_records([[a, b]], seen) == []

    def test_identity_filter_is_per_record(self):
        rec = _rec(0, replica_id=1, role='prefill')
        assert cli._log_record_matches(rec, None, None)
        assert cli._log_record_matches(rec, 1, 'prefill')
        assert not cli._log_record_matches(rec, 2, None)
        assert not cli._log_record_matches(rec, 1, 'decode')

    def test_format_prefixes_identity(self):
        line = cli._fmt_log_record(
            _rec(0, replica_id=4, role='decode', request_id='r-9'))
        assert '[replica 4 (decode)]' in line
        assert line.endswith('(req r-9)')
        assert 'line 0' in line
        assert '[lb]' in cli._fmt_log_record(_rec(1, process='lb'))

    def test_interleave_logs_slots_lines_into_waterfall(self):
        segments = [
            {'name': 'lb', 'process': 'lb', 'start': 1000.0,
             'duration_ms': 10.0,
             'phases': [{'name': 'route', 'start': 1000.0,
                         'duration_ms': 1.0}]},
            {'name': 'engine', 'replica_id': 1, 'role': 'decode',
             'start': 1000.002, 'duration_ms': 8.0, 'phases': []},
        ]
        records = [_rec(0, ts=1000.004, process='replica',
                        replica_id=1, role='decode')]
        out = traces_lib.interleave_logs(segments, records)
        text = '\n'.join(out)
        assert 'lb' in text and 'engine' in text
        assert '[replica 1 (decode)] I unit: line 0' in text
        # The log line lands AFTER the engine row it belongs under.
        engine_row = next(i for i, line in enumerate(out)
                          if 'engine' in line)
        log_row = next(i for i, line in enumerate(out)
                       if 'line 0' in line)
        assert log_row > engine_row
        # Without segments the records still render, never crash.
        only_logs = traces_lib.interleave_logs([], records)
        assert any('line 0' in line for line in only_logs)
        assert traces_lib.interleave_logs([], []) == ['(no segments)']


# ---------------------------------------------------------------- budget

class TestLogHandlerOverheadBudget:
    """ISSUE 19 acceptance: the structured handler may cost at most 3%
    of a tick's work.  Same factored A/B as the profiler budget
    (TestOverheadBudget in test_profiling.py): wall-clocking two full
    workloads is hopeless on a noisy CI box, so the marginal per-record
    cost comes from a tight with/without-handler microbenchmark and is
    asserted against a measured representative tick's compute."""

    TICKS = 4000

    @classmethod
    def _per_record_cost(cls, logger):
        t0 = time.perf_counter()
        for _ in range(cls.TICKS):
            logger.info('tick access line')
        return (time.perf_counter() - t0) / cls.TICKS

    def test_handler_overhead_within_3_percent(self):
        on = logging.Logger('skytpu_log_overhead_on', logging.INFO)
        off = logging.Logger('skytpu_log_overhead_off', logging.INFO)
        # Both arms pay record creation + one no-op handler; only the
        # `on` arm pays the structured capture being budgeted.
        for arm in (on, off):
            arm.propagate = False
            arm.addHandler(logging.NullHandler())
        on.addHandler(logs_lib.StructuredLogHandler(
            ring=logs_lib.LogRecordRing(maxlen=2048)))
        self._per_record_cost(on), self._per_record_cost(off)  # warm-up
        marginal = min(self._per_record_cost(on) -
                       self._per_record_cost(off) for _ in range(5))

        def tick_work():
            t0 = time.perf_counter()
            assert sum(range(30000)) > 0
            return time.perf_counter() - t0
        work = min(tick_work() for _ in range(20))
        assert marginal <= 0.03 * work, (marginal, work)
