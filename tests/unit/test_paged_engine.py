"""Paged-KV engine tests: paged/dense logits parity (greedy + sampled,
native + int8 pages), chunked prefill across page boundaries, prefix
reuse with mid-page divergence, pool exhaustion -> 429 backpressure,
and no page leaks across completion/cancel/TTL.

Engines are module-scoped where possible: every engine instance
re-jits the paged step, so tests share one plain and one int8 engine
(using disjoint token ranges so prefix-cache state cannot couple
them) and only pool-accounting tests build their own small pools."""
from __future__ import annotations

import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs
from skypilot_tpu.models import decode
from skypilot_tpu.models.transformer import Transformer
from skypilot_tpu.serve import batching_engine
from skypilot_tpu.serve import cache_manager


@pytest.fixture(scope='module')
def setup():
    cfg = configs.get_config('tiny')
    model = Transformer(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))['params'])
    return cfg, params


def _reference(cfg, params, prompt_ids, n, max_len=64):
    prompt = jnp.asarray([prompt_ids], jnp.int32)
    _, new = decode.generate(cfg, params, prompt, max_new_tokens=n,
                             max_len=max_len)
    return [int(t) for t in np.asarray(new)[0]]


def _paged_engine(cfg, params, **kw):
    kw.setdefault('max_len', 64)
    kw.setdefault('slots', 2)
    kw.setdefault('prefill_chunk', 8)
    kw.setdefault('kv_pages', 48)
    kw.setdefault('page_size', 8)
    return batching_engine.ContinuousBatchingEngine(cfg, params, **kw)


@pytest.fixture(scope='module')
def plain_engine(setup):
    cfg, params = setup
    eng = _paged_engine(cfg, params)
    yield eng
    eng.stop()


@pytest.fixture(scope='module')
def int8_engine(setup):
    cfg, params = setup
    eng = _paged_engine(cfg, params, quantize_kv=True)
    yield eng
    eng.stop()


class TestPagedParity:

    def test_greedy_parity_vs_dense_generate(self, setup,
                                             plain_engine):
        """Greedy decode through the page pool must match the dense
        single-sequence reference token-for-token (same masked
        attention over the same values, gathered by page index)."""
        cfg, params = setup
        for prompt, n in (([3, 1, 4, 1, 5, 9, 2, 6], 6),
                          ([7], 4),        # single-token prompt
                          ([2, 7], 8),
                          (list(range(1, 25)), 5)):  # multi-page
            got = plain_engine.generate(prompt, n, timeout=180)
            assert got == _reference(cfg, params, prompt, n), prompt

    def test_greedy_parity_int8_kv(self, setup, int8_engine):
        """int8 pages must still agree with the dense reference on the
        tiny config's logit margins (the acceptance pin)."""
        cfg, params = setup
        for prompt, n in (([3, 1, 4, 1, 5, 9, 2, 6], 6),
                          ([7], 4),
                          (list(range(1, 25)), 5)):
            got = int8_engine.generate(prompt, n, timeout=180)
            assert got == _reference(cfg, params, prompt, n), prompt

    def test_concurrent_requests_exact(self, setup, plain_engine):
        cfg, params = setup
        prompts = [([3, 1, 4, 1, 5], 5), ([2, 7], 8),
                   ([9, 9, 8, 2, 1, 0, 3], 3)]
        requests = [plain_engine.submit(p, n) for p, n in prompts]
        results = [r.result(timeout=180) for r in requests]
        for (p, n), got in zip(prompts, results):
            assert got == _reference(cfg, params, p, n), (p, n)

    def test_sampled_parity_vs_dense_engine(self, setup, plain_engine):
        """Temperature sampling depends only on (logits, key chain);
        paged at a given seed must match the dense single-sequence
        path — sampled-path parity for the page gather.  (The dense
        engine's row-parity vs decode.generate's sampling is pinned in
        test_batching_engine; generate() is the shared reference.)"""
        cfg, params = setup
        sampling = decode.SamplingConfig(temperature=0.8, top_k=10,
                                         seed=123)
        prompt = [3, 1, 4, 1, 5, 9, 2]
        a = plain_engine.generate(prompt, 6, sampling=sampling,
                                  timeout=180)
        b = plain_engine.generate(prompt, 6, sampling=sampling,
                                  timeout=180)
        assert a == b          # seed-deterministic through pages
        assert len(a) == 6
        greedy = plain_engine.generate(
            prompt, 5, timeout=180,
            sampling=decode.SamplingConfig(temperature=0.0))
        assert greedy == _reference(cfg, params, prompt, 5)

    def test_chunked_prefill_across_page_boundaries(self, setup):
        """Chunk width (6) deliberately misaligned with page size (8):
        chunk boundaries land mid-page and page boundaries mid-chunk —
        the scatter/gather must stay exact either way."""
        cfg, params = setup
        eng = _paged_engine(cfg, params, prefill_chunk=6)
        try:
            for prompt in (list(range(1, 21)),   # 19 = 3 chunks + tail
                           [7, 9]):
                got = eng.generate(prompt, 5, timeout=180)
                assert got == _reference(cfg, params, prompt, 5), prompt
            assert eng.stats()['prefill_chunks'] >= 3
        finally:
            eng.stop()

    def test_moe_paged_exact(self):
        """MoE + pages: full-prompt prefill scatters into pages (no
        prefix reuse — the capacity dispatch couples KV to the whole
        prompt) and decode stays exact."""
        cfg = configs.get_config('tiny-moe')
        prompt = [3, 1, 4, 1, 5, 9, 2]
        params = nn.meta.unbox(Transformer(cfg).init(
            jax.random.PRNGKey(0),
            jnp.asarray([prompt], jnp.int32))['params'])
        eng = _paged_engine(cfg, params)
        try:
            got = eng.generate(prompt, 5, timeout=180)
            assert got == _reference(cfg, params, prompt, 5)
            assert eng.stats()['prefix_cache_entries'] == 0
        finally:
            eng.stop()


class TestInt8KVBound:

    def test_int8_logits_divergence_bounded(self, setup):
        """int8 KV vs native KV: the step logits may drift but must
        stay within a small relative error of the dense reference —
        the quantization-noise contract behind the greedy-parity pin."""
        cfg, params = setup
        prompt = jnp.asarray([list(range(1, 17))], jnp.int32)
        ref_logits, _ = decode.prefill(cfg, params, prompt, max_len=32)

        ps, n_pages = 8, 8
        paged = decode.init_paged_cache(cfg, n_pages, ps, 1, 4,
                                        quantize_kv=True)
        _, priv = decode.prefill(cfg, params, prompt, max_len=32)
        pages = jnp.asarray([1, 2, 3, 4], jnp.int32)
        paged = decode.insert_prefill_pages(paged, priv, pages,
                                            first_page=0)
        row = jnp.zeros((4,), jnp.int32).at[:4].set(pages)
        paged = decode.paged_admit_slot(paged, 0, row, 15)
        logits, _ = decode.paged_batched_step(
            cfg, params, prompt[:, -1:], paged)
        ref = np.asarray(ref_logits)[0]
        got = np.asarray(logits)[0]
        rel = np.max(np.abs(got - ref)) / max(np.max(np.abs(ref)), 1e-9)
        assert rel < 0.05, rel
        # ...and small enough that greedy agrees here.
        assert int(np.argmax(got)) == int(np.argmax(ref))


class TestPrefixReuse:
    # Each test uses its own token range so shared-engine cache state
    # cannot couple tests.

    def test_identical_prompts_hit_and_stay_exact(self, setup,
                                                  plain_engine):
        cfg, params = setup
        eng = plain_engine
        shared = list(range(40, 80))            # 40 tokens -> 4 pages
        a = eng.generate(shared, 5, timeout=180)
        hits0 = eng.stats()['prefix_cache_hits']
        handle = eng.submit(shared, 5)
        b = handle.result(timeout=180)
        assert a == b == _reference(cfg, params, shared, 5)
        stats = eng.stats()
        assert stats['prefix_cache_hits'] == hits0 + 4
        assert stats['prefix_cache_entries'] >= 4
        # The hit is visible on the request's span.
        span = eng.span(handle.request_id)
        assert span['prefix_hit_pages'] == 4
        assert span['prefill_chunks'] <= 2       # seed + tail only

    def test_mid_page_divergence_correct(self, setup, int8_engine):
        """Two sessions share a prefix that ends MID-page: the shared
        full pages reuse, the divergence page is private per session,
        and both decode exactly (int8 pages — the quantized gather
        must honor the same sharing rules)."""
        cfg, params = setup
        eng = int8_engine
        base = list(range(100, 140))            # 40 tokens, ps=8
        s1 = base[:37] + [5, 6, 7]              # diverge at pos 37
        s2 = base[:37] + [8, 9, 1]              # (mid page 5)
        a = eng.generate(s1, 5, timeout=180)
        hits0 = eng.stats()['prefix_cache_hits']
        b = eng.generate(s2, 5, timeout=180)
        assert a == _reference(cfg, params, s1, 5)
        assert b == _reference(cfg, params, s2, 5)
        # s2 shared s1's 4 full pages, not the divergence page.
        assert eng.stats()['prefix_cache_hits'] >= hits0 + 4

    def test_full_hit_skips_prefill_entirely(self, setup,
                                             plain_engine):
        """A page-aligned fully-cached prefix admits with ZERO prefill
        chunks — the TTFT-collapse mechanism."""
        cfg, params = setup
        eng = plain_engine
        prompt = list(range(150, 183))          # n-1 = 32 = 4 pages
        eng.generate(prompt, 4, timeout=180)
        chunks0 = eng.stats()['prefill_chunks']
        handle = eng.submit(prompt, 4)
        got = handle.result(timeout=180)
        assert got == _reference(cfg, params, prompt, 4)
        assert eng.stats()['prefill_chunks'] == chunks0
        assert eng.span(handle.request_id)['prefix_hit_pages'] == 4

    def test_hit_tail_shorter_than_chunk(self, setup):
        """Regression: a prefix hit seeds the private cache near the
        end of the prompt, so the remaining tail can be far shorter
        than prefill_chunk — with the default chunk (512) wider than
        max_len (128) the continuation piece must be narrowed to fit
        the cache instead of clamping over the seeded prefix."""
        cfg, params = setup
        eng = _paged_engine(cfg, params, max_len=128,
                            prefill_chunk=512, slots=2)
        try:
            shared = list(range(30, 90))        # 60 tokens, ps=8
            a = eng.generate(shared, 5, timeout=180)
            b = eng.generate(shared, 5, timeout=180)  # hit: tail of 3
            assert a == b == _reference(cfg, params, shared, 5,
                                        max_len=128)
        finally:
            eng.stop()

    def test_prefix_cache_disabled(self, setup):
        cfg, params = setup
        eng = _paged_engine(cfg, params, prefix_caching=False,
                            slots=1)
        try:
            shared = list(range(40, 60))
            a = eng.generate(shared, 4, timeout=180)
            b = eng.generate(shared, 4, timeout=180)
            assert a == b == _reference(cfg, params, shared, 4)
            stats = eng.stats()
            assert stats['prefix_cache_hits'] == 0
            assert stats['prefix_cache_entries'] == 0
        finally:
            eng.stop()


class TestPoolAccounting:

    def test_pages_freed_on_completion_cancel_and_ttl(self, setup):
        cfg, params = setup
        eng = _paged_engine(cfg, params, slots=1, queue_ttl=0.05,
                            prefix_caching=False)
        try:
            done = eng.submit(list(range(1, 20)), 20)
            stale = eng.submit([4, 5], 4)        # expires queued (TTL)
            with pytest.raises(batching_engine.QueueExpired):
                stale.result(timeout=60)
            # Cancel the long request mid-decode.
            stream = done.stream(timeout=60)
            next(stream)
            done.cancel()
            assert done.done.wait(30)
            deadline = time.time() + 30
            while (eng.stats()['kv_pages_used'] > 0 and
                   time.time() < deadline):
                time.sleep(0.01)
            assert eng.stats()['kv_pages_used'] == 0
            # The pool is fully reusable afterwards.
            got = eng.generate([4, 5], 3, timeout=60)
            assert got == _reference(cfg, params, [4, 5], 3)
        finally:
            eng.stop()
        assert eng._kv.pool.used_count == 0  # pylint: disable=protected-access

    def test_cancel_mid_prefill_frees_pages(self, setup):
        cfg, params = setup
        eng = _paged_engine(cfg, params, slots=1, prefill_chunk=4,
                            prefix_caching=False)
        try:
            blocker = eng.submit(list(range(1, 25)), 6)
            victim = eng.submit(list(range(1, 20)), 6)
            victim.cancel()
            assert blocker.result(timeout=180) == _reference(
                cfg, params, list(range(1, 25)), 6)
            assert victim.done.wait(60)
            deadline = time.time() + 30
            while (eng.stats()['kv_pages_used'] > 0 and
                   time.time() < deadline):
                time.sleep(0.01)
            assert eng.stats()['kv_pages_used'] == 0
        finally:
            eng.stop()

    def test_exhaustion_backpressures_with_429_class(self, setup):
        """Pool too small for two concurrent requests: the second
        stays queued (not crashed), and a third submit gets QueueFull
        (the HTTP 429 mapping) with Retry-After while the pool is
        exhausted.  Also covers submit-time rejection of requests that
        could NEVER fit."""
        cfg, params = setup
        eng = _paged_engine(cfg, params, kv_pages=6, page_size=8,
                            slots=2, prefix_caching=False)
        try:
            with pytest.raises(ValueError, match='pool capacity'):
                eng.submit(list(range(1, 40)), 20)   # needs 8 of 5
            # 4 pages: 25 prompt + 7 new -> ceil(31/8) = 4 of 5 usable.
            blocker = eng.submit(list(range(1, 26)), 7)
            deadline = time.time() + 30
            while (eng.stats()['kv_pages_used'] < 4 and
                   time.time() < deadline):
                time.sleep(0.005)
            queued = eng.submit(list(range(1, 20)), 8)   # needs 4
            # The worker must DEFER the queued request (pool can't
            # cover it while the blocker holds pages) — poll rather
            # than sleep: first-time compiles can stall the loop.
            deadline = time.time() + 60
            while (eng.stats()['pages_exhausted_deferrals'] < 1 and
                   not queued.done.is_set() and
                   time.time() < deadline):
                time.sleep(0.005)
            if not queued.done.is_set():
                assert eng.stats()['pages_exhausted_deferrals'] >= 1
                with pytest.raises(batching_engine.QueueFull) as err:
                    eng.submit(list(range(1, 20)), 8)
                assert err.value.retry_after >= 1.0
            assert eng.stats()['failed'] is False
            # The blocker finishing frees pages; the queued request
            # must then complete on its own.
            assert blocker.result(timeout=120) == _reference(
                cfg, params, list(range(1, 26)), 7)
            assert queued.result(timeout=120) == _reference(
                cfg, params, list(range(1, 20)), 8)
        finally:
            eng.stop()

    def test_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match='multiple'):
            batching_engine.ContinuousBatchingEngine(
                cfg, params, max_len=60, kv_pages=16, page_size=8)
        with pytest.raises(ValueError, match='pipelined'):
            batching_engine.ContinuousBatchingEngine(
                cfg, params, max_len=64, kv_pages=16, page_size=8,
                pipelined=False)


class TestStatsAndMetrics:

    def test_paged_stats_and_gauges(self, setup, plain_engine):
        from skypilot_tpu.observability import metrics as metrics_lib
        stats = plain_engine.stats()
        assert stats['paged'] is True
        assert stats['kv_pages_total'] == 47
        assert stats['page_size'] == 8
        assert stats['prefix_cache_misses'] >= 0
        text = metrics_lib.expose()
        for name in ('skytpu_engine_kv_pages_total',
                     'skytpu_engine_kv_pages_used',
                     'skytpu_engine_kv_pages_pinned',
                     'skytpu_engine_prefix_cache_hits_total',
                     'skytpu_engine_prefix_cache_misses_total'):
            assert name in text, name
        parsed = metrics_lib.parse_exposition(text)
        assert sum(parsed['skytpu_engine_kv_pages_total']
                   .values()) == 47

    def test_dense_engine_unaffected(self, setup):
        """A dense engine reports paged=False and no page keys —
        the facade split must not change the dense contract."""
        cfg, params = setup
        eng = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=32, slots=1)
        try:
            stats = eng.stats()
            assert stats['paged'] is False
            assert 'kv_pages_total' not in stats
        finally:
            eng.stop()


class TestFacadeCompat:

    def test_legacy_names_still_importable(self):
        """The batching_engine facade keeps the pre-split import
        surface (ROADMAP satellite: existing imports keep working)."""
        from skypilot_tpu.serve import sampler
        from skypilot_tpu.serve import scheduler
        assert batching_engine.QueueFull is scheduler.QueueFull
        assert batching_engine.QueueExpired is scheduler.QueueExpired
        assert batching_engine._Request is scheduler.Request  # pylint: disable=protected-access
        assert batching_engine._Slot is scheduler.Slot  # pylint: disable=protected-access
        assert batching_engine._PendingPrefill is scheduler.PendingPrefill  # pylint: disable=protected-access
        assert batching_engine.PagesExhausted is (
            cache_manager.PagesExhausted)
        assert sampler.validate_sampling(None, max_top_k=4,
                                         pipelined=True) == (0.0, 0, 0)
