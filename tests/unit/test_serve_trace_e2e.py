"""End-to-end distributed trace assembly through the real LB
(ISSUE 11 acceptance): a prefill->handoff->decode request stitched
from all three processes' span exports in causal order, and the
retry path (attempt 0 vs attempt 1) kept distinct.
"""
from __future__ import annotations

import socket

import pytest
import requests

from skypilot_tpu.observability import traces as traces_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import model_server as model_server_lib
from skypilot_tpu.serve import router as router_lib


def _make_server(role, replica_id):
    return model_server_lib.ModelServer(
        'tiny', max_len=64, max_batch=2, continuous_batching=True,
        kv_pages=48, page_size=8, prefill_chunk=16, role=role,
        replica_id=replica_id)


def _dead_url() -> str:
    """A url nothing listens on (bound then closed)."""
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    return f'http://127.0.0.1:{port}'


def test_disaggregated_request_stitches_three_processes():
    """`sky serve trace` substance: LB + prefill replica + decode
    replica segments assemble into one causal waterfall, and the
    Chrome export is a valid trace."""
    prefill = _make_server('prefill', 1)
    decode = _make_server('decode', 2)
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', router=router_lib.Router(threshold=24))
    shutdowns = []
    try:
        p_port, p_stop = model_server_lib.start_background(prefill)
        d_port, d_stop = model_server_lib.start_background(decode)
        shutdowns.extend([p_stop, d_stop])
        lb.set_replicas([
            {'url': f'http://127.0.0.1:{p_port}', 'role': 'prefill',
             'page_size': 8},
            {'url': f'http://127.0.0.1:{d_port}', 'role': 'decode',
             'page_size': 8},
        ])
        lb_port = lb.start()
        prompt = list(range(1, 41))   # above threshold -> handoff
        resp = requests.post(
            f'http://127.0.0.1:{lb_port}/generate',
            json={'prompt_ids': [prompt], 'max_new_tokens': 4},
            timeout=120)
        assert resp.status_code == 200
        rid = resp.headers['X-SkyTPU-Request-Id']

        targets = [
            {'url': f'http://127.0.0.1:{p_port}', 'replica_id': 1,
             'role': 'prefill'},
            {'url': f'http://127.0.0.1:{d_port}', 'replica_id': 2,
             'role': 'decode'},
        ]
        segments = traces_lib.collect(
            rid, targets, f'http://127.0.0.1:{lb_port}')
        by_name = {s['name']: s for s in segments}
        # All three processes contributed.
        assert by_name['lb']['process'] == 'lb'
        assert by_name['prefill_export']['replica_id'] == 1
        assert by_name['kv_import']['replica_id'] == 2
        assert by_name['engine']['replica_id'] == 2
        # Causal order: LB first, prefill export before the decode
        # replica's import, engine span last.
        names = [s['name'] for s in segments]
        assert names.index('lb') == 0
        assert names.index('prefill_export') < names.index('kv_import')
        assert names.index('kv_import') < names.index('engine')
        # LB segment carries the route/handoff/attempt phases.
        lb_phases = [p['name'] for p in by_name['lb']['phases']]
        assert lb_phases[:2] == ['route', 'handoff']
        assert 'attempt-0' in lb_phases
        assert by_name['lb']['status'] == 200
        # Engine span kept its routed facts + handoff timing.
        assert by_name['engine']['routed_role'] == 'decode'
        assert by_name['engine']['handoff_ms'] > 0
        # Waterfall renders every process; Chrome export is valid.
        text = '\n'.join(traces_lib.format_waterfall(segments))
        assert 'replica 1 (prefill)' in text
        assert 'replica 2 (decode)' in text
        events = traces_lib.to_chrome_trace(segments)
        assert {e['args']['name'] for e in events
                if e['ph'] == 'M'} == {
                    'lb', 'replica 1 (prefill)',
                    'replica 2 (decode)'}
        # The since= filter excludes everything already exported.
        assert traces_lib.fetch_segments(
            f'http://127.0.0.1:{p_port}', request_id=rid,
            since=9e12) == []
    finally:
        lb.stop()
        for stop in shutdowns:
            stop()
        prefill.close()
        decode.close()


def test_retry_attempts_stay_distinct():
    """A dead first target forces the LB's one-shot same-role retry:
    the reused request id shows up as attempt-0 (upstream_error) and
    attempt-1 (served), and the replica span is tagged attempt=1."""
    alive = _make_server('decode', 5)
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:1',
                                     router=router_lib.Router(
                                         threshold=1000))
    try:
        a_port, a_stop = model_server_lib.start_background(alive)
        dead = _dead_url()
        # The dead replica ranks first (load 0 vs 0.9) so attempt 0
        # hits it and fails before any byte.
        lb.set_replicas([
            {'url': dead, 'role': 'decode', 'load': 0.0},
            {'url': f'http://127.0.0.1:{a_port}', 'role': 'decode',
             'load': 0.9},
        ])
        lb_port = lb.start()
        resp = requests.post(
            f'http://127.0.0.1:{lb_port}/generate',
            json={'prompt_ids': [[1, 2, 3]], 'max_new_tokens': 3},
            timeout=120)
        assert resp.status_code == 200
        rid = resp.headers['X-SkyTPU-Request-Id']
        [lb_seg] = traces_lib.fetch_segments(
            f'http://127.0.0.1:{lb_port}', '/lb/spans',
            request_id=rid)
        phases = {p['name']: p for p in lb_seg['phases']}
        assert phases['attempt-0']['status'] == 'upstream_error'
        assert phases['attempt-0']['target'] == dead
        assert phases['attempt-1']['status'] == 200
        # The replica's span names the retry attempt, so assembly
        # can't conflate it with the (never-served) first attempt.
        [engine_seg] = traces_lib.fetch_segments(
            f'http://127.0.0.1:{a_port}', request_id=rid)
        assert engine_seg['attempt'] == 1
        assert engine_seg['replica_id'] == 5
    finally:
        lb.stop()
        a_stop()
        alive.close()


@pytest.mark.slow
def test_streaming_request_traced_through_async_front():
    """Heavy variant: the async front's SSE stream also exports its
    span, assembled with the LB segment."""
    from skypilot_tpu.serve import async_server

    server = _make_server('mixed', 3)
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:1',
                                     router=router_lib.Router(
                                         threshold=1000))
    try:
        port, stop = async_server.start_background(server)
        lb.set_replicas([{'url': f'http://127.0.0.1:{port}',
                          'role': 'mixed'}])
        lb_port = lb.start()
        resp = requests.post(
            f'http://127.0.0.1:{lb_port}/generate_stream',
            json={'prompt_ids': [[1, 2, 3, 4]], 'max_new_tokens': 4},
            timeout=120, stream=True)
        assert resp.status_code == 200
        list(resp.iter_content(1024))    # drain the stream
        rid = resp.headers['X-SkyTPU-Request-Id']
        segments = traces_lib.collect(
            rid, [{'url': f'http://127.0.0.1:{port}',
                   'replica_id': 3, 'role': 'mixed'}],
            f'http://127.0.0.1:{lb_port}')
        names = [s['name'] for s in segments]
        assert 'lb' in names and 'engine' in names
        engine_seg = next(s for s in segments
                          if s['name'] == 'engine')
        assert engine_seg['tokens'] == 4
    finally:
        lb.stop()
        stop()
        server.close()
