"""Managed-jobs tests: controller supervision, recovery, cancel.

Hermetic per SURVEY.md §4's improvement note: the local provisioner
stands in for the cloud, so preemption is simulated by terminating the
task cluster behind the controller's back — something the reference can
only test with real spot instances in smoke tests.
"""
from __future__ import annotations

import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import global_user_state
from skypilot_tpu.jobs import controller as controller_lib
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state
from skypilot_tpu.jobs.state import ManagedJobStatus


@pytest.fixture(autouse=True)
def _fast_polls(monkeypatch, _isolated_home):
    monkeypatch.setenv('SKYTPU_JOB_STATUS_CHECK_GAP', '0.3')
    monkeypatch.setenv('SKYTPU_JOB_STARTED_CHECK_GAP', '0.3')
    monkeypatch.setenv('SKYTPU_MANAGED_JOB_DB',
                       str(_isolated_home / 'managed_jobs.db'))
    global_user_state.set_enabled_clouds(['local'])
    yield


def _local_task(name='mjob', run='echo done', **kwargs):
    task = sky.Task(name=name, run=run, **kwargs)
    task.set_resources(sky.Resources(cloud='local'))
    return task


def _run_controller(job_id):
    """Run the controller inline (not as a daemon) for determinism."""
    records = state.get_job_records(job_id)
    controller_lib.JobsController(job_id, records[0]['dag_yaml_path']).run()


def _submit(task_or_dag, name=None):
    """Submit without spawning the daemon (controller run inline)."""
    from skypilot_tpu import config as config_lib
    import skypilot_tpu.jobs.constants as jc
    from skypilot_tpu.utils import dag_utils
    dag = dag_utils.convert_entrypoint_to_dag(task_or_dag)
    job_name = name or dag.name or 'mjob'
    job_id = state.allocate_job_id(job_name)
    yaml_path = os.path.join(jobs_core._dag_yaml_dir(),  # pylint: disable=protected-access
                             f'{job_name}-{job_id}.yaml')
    dag_utils.dump_chain_dag_to_yaml(dag, yaml_path)
    state.submit_job(job_id, job_name, yaml_path,
                     [t.name or f'task-{i}'
                      for i, t in enumerate(dag.tasks)])
    state.set_status(job_id, 0, ManagedJobStatus.SUBMITTED)
    return job_id


class TestStateMachine:

    def test_terminal_classification(self):
        assert ManagedJobStatus.SUCCEEDED.is_terminal()
        assert ManagedJobStatus.FAILED.is_failed()
        assert not ManagedJobStatus.RECOVERING.is_terminal()

    def test_submit_and_status(self):
        job_id = _submit(_local_task())
        assert state.get_status(job_id) is ManagedJobStatus.SUBMITTED
        assert job_id in state.get_nonterminal_job_ids()

    def test_recovery_count(self):
        job_id = _submit(_local_task())
        state.set_recovering(job_id, 0)
        rec = state.get_job_records(job_id)[0]
        assert rec['recovery_count'] == 1
        assert rec['status'] == 'RECOVERING'


class TestStrategySelection:

    def test_default_strategy(self):
        ex = recovery_strategy.StrategyExecutor.make('c', _local_task())
        assert ex.NAME == 'EAGER_NEXT_REGION'

    def test_failover_strategy(self):
        task = sky.Task(name='t', run='true')
        task.set_resources(
            sky.Resources(cloud='local', job_recovery='failover'))
        ex = recovery_strategy.StrategyExecutor.make('c', task)
        assert ex.NAME == 'FAILOVER'

    def test_unknown_strategy_rejected(self):
        task = sky.Task(name='t', run='true')
        task.set_resources(
            sky.Resources(cloud='local', job_recovery='bogus'))
        with pytest.raises(Exception):
            recovery_strategy.StrategyExecutor.make('c', task)

    def test_elastic_strategy_selected(self):
        task = sky.Task(name='t', run='true')
        task.set_resources(
            sky.Resources(cloud='local', job_recovery='elastic'))
        ex = recovery_strategy.StrategyExecutor.make('c', task)
        assert ex.NAME == 'ELASTIC'


class TestFailoverRegionPin:
    """ISSUE 6 satellite: `_launch(prefer_same_region=True)` was a
    silent no-op (the flag was `del`'d); the same-region attempt must
    actually pin the previous launch's region/zone and the fallback
    must clear the pin — proven by the requests the optimizer sees."""

    def _strategy_with_history(self, monkeypatch, seen):
        task = sky.Task(name='t', run='true')
        task.set_resources(sky.Resources(job_recovery='failover'))
        ex = recovery_strategy.StrategyExecutor.make('c', task)
        ex._last_region = 'region-prev'  # pylint: disable=protected-access
        ex._last_zone = 'zone-prev'  # pylint: disable=protected-access
        monkeypatch.setattr(ex, 'cleanup_cluster', lambda: None)
        monkeypatch.setattr(recovery_strategy.time, 'sleep',
                            lambda _: None)

        def fake_launch(task, **kwargs):
            del kwargs
            resources = next(iter(task.resources))
            seen.append((resources.region, resources.zone))
            raise sky.exceptions.ResourcesUnavailableError('no capacity')

        from skypilot_tpu import execution
        monkeypatch.setattr(execution, 'launch', fake_launch)
        return ex

    def test_same_region_attempt_pins_then_fallback_unpins(
            self, monkeypatch):
        seen = []
        ex = self._strategy_with_history(monkeypatch, seen)
        with pytest.raises(sky.exceptions.ResourcesUnavailableError):
            ex._do_recover()  # pylint: disable=protected-access
        # 3 pinned attempts (same-region phase), then 3 unpinned
        # (full-search fallback): the optimizer request DIFFERS.
        assert seen[:3] == [('region-prev', 'zone-prev')] * 3
        assert seen[3:] == [(None, None)] * 3

    def test_pin_restored_after_launch(self, monkeypatch):
        """The task's own resources are never left mutated, even when
        the pinned attempt raises."""
        seen = []
        ex = self._strategy_with_history(monkeypatch, seen)
        with pytest.raises(sky.exceptions.ResourcesUnavailableError):
            ex._do_recover()  # pylint: disable=protected-access
        resources = next(iter(ex.task.resources))
        assert resources.region is None and resources.zone is None

    def test_no_history_launches_unpinned(self, monkeypatch):
        seen = []
        ex = self._strategy_with_history(monkeypatch, seen)
        ex._last_region = None  # pylint: disable=protected-access
        ex._last_zone = None  # pylint: disable=protected-access
        with pytest.raises(sky.exceptions.ResourcesUnavailableError):
            ex._do_recover()  # pylint: disable=protected-access
        assert all(r == (None, None) for r in seen)


class TestControllerE2E:

    def test_job_succeeds(self):
        job_id = _submit(_local_task(run='echo MANAGED_OK'))
        _run_controller(job_id)
        assert state.get_status(job_id) is ManagedJobStatus.SUCCEEDED
        # Task cluster cleaned up after success.
        assert sky.status() == []

    def test_user_failure_marks_failed(self):
        job_id = _submit(_local_task(run='exit 3'))
        _run_controller(job_id)
        assert state.get_status(job_id) is ManagedJobStatus.FAILED

    def test_chain_dag_runs_in_order(self):
        with sky.Dag() as dag:
            a = _local_task(name='first', run='echo A')
            b = _local_task(name='second', run='echo B')
            a >> b  # pylint: disable=pointless-statement
        job_id = _submit(dag, name='chain')
        _run_controller(job_id)
        records = state.get_job_records(job_id)
        assert [r['status'] for r in records] == ['SUCCEEDED', 'SUCCEEDED']

    def test_chain_stops_after_failure(self):
        with sky.Dag() as dag:
            a = _local_task(name='first', run='exit 1')
            b = _local_task(name='second', run='echo B')
            a >> b  # pylint: disable=pointless-statement
        job_id = _submit(dag, name='chain-fail')
        _run_controller(job_id)
        records = state.get_job_records(job_id)
        assert records[0]['status'] == 'FAILED'
        assert records[1]['status'] == 'CANCELLED'

    def test_preemption_recovery(self, monkeypatch):
        """Kill the task cluster mid-run; the controller must relaunch
        it and the job must still succeed (checkpoint-style resume)."""
        marker = os.path.join(os.environ['SKYTPU_HOME'], 'ran_twice')
        # First run sleeps long; after 'preemption' the relaunched run
        # finds the marker and exits quickly.
        run_cmd = (f'if [ -f {marker} ]; then echo RESUMED; '
                   f'else touch {marker} && sleep 60; fi')
        job_id = _submit(_local_task(name='preempt', run=run_cmd))

        preempted = {'done': False}
        orig_query = controller_lib.JobsController._query_job_status

        def query_and_preempt(self, cluster_name, remote_job_id):
            status = orig_query(self, cluster_name, remote_job_id)
            if not preempted['done'] and os.path.exists(marker):
                preempted['done'] = True
                sky.down(cluster_name)   # simulate slice eviction
                return None
            return status

        monkeypatch.setattr(controller_lib.JobsController,
                            '_query_job_status', query_and_preempt)
        _run_controller(job_id)
        assert preempted['done']
        rec = state.get_job_records(job_id)[0]
        assert rec['status'] == 'SUCCEEDED'
        assert rec['recovery_count'] >= 1

    def test_restart_exhaustion_persists_reason_and_journals(
            self, monkeypatch):
        """ISSUE 5 satellite: exhausting max_restarts_on_errors lands a
        terminal FAILED with the exhaustion reason persisted (not just
        logged) and a recovery_exhausted journal event."""
        from skypilot_tpu.observability import events as events_lib
        orig_make = recovery_strategy.StrategyExecutor.make.__func__

        def make_with_budget(cls, cluster_name, task, job_id=None,
                             task_id=0):
            strategy = orig_make(cls, cluster_name, task, job_id=job_id,
                                 task_id=task_id)
            strategy.max_restarts_on_errors = 1
            return strategy

        monkeypatch.setattr(recovery_strategy.StrategyExecutor, 'make',
                            classmethod(make_with_budget))
        job_id = _submit(_local_task(name='exhaust', run='exit 9'))
        _run_controller(job_id)
        rec = state.get_job_records(job_id)[0]
        assert rec['status'] == 'FAILED'
        assert rec['recovery_count'] == 1  # one restart was attempted
        assert 'max_restarts_on_errors exhausted (1/1)' in \
            rec['last_recovery_reason']
        assert 'max_restarts_on_errors exhausted' in \
            rec['failure_reason']
        events = events_lib.job_events(job_id)
        exhausted = [e for e in events
                     if e['event'] == 'recovery_exhausted']
        assert len(exhausted) == 1
        assert exhausted[0]['restarts'] == 1
        assert exhausted[0]['max_restarts'] == 1

    def test_cancel_requested_mid_run(self):
        job_id = _submit(_local_task(name='cancelme', run='sleep 60'))
        # Request cancellation as soon as the controller marks RUNNING.
        import threading

        def canceller():
            for _ in range(100):
                if state.get_status(job_id) is ManagedJobStatus.RUNNING:
                    jobs_core.cancel([job_id])
                    return
                time.sleep(0.1)

        t = threading.Thread(target=canceller)
        t.start()
        _run_controller(job_id)
        t.join()
        assert state.get_status(job_id) is ManagedJobStatus.CANCELLED
        assert sky.status() == []


class TestClientAPI:

    def test_queue_lists_jobs(self):
        job_id = _submit(_local_task(run='echo ok'))
        _run_controller(job_id)
        records = jobs_core.queue()
        assert any(r['job_id'] == job_id and r['status'] == 'SUCCEEDED'
                   for r in records)

    def test_cancel_terminal_job_noop(self):
        job_id = _submit(_local_task(run='echo ok'))
        _run_controller(job_id)
        assert jobs_core.cancel([job_id]) == []

    def test_launch_detached_process_mode(self):
        """Full client path: spawns the controller daemon for real."""
        job_id = jobs_core.launch(_local_task(name='detached',
                                              run='echo DETACHED_OK'))
        deadline = time.time() + 60
        while time.time() < deadline:
            status = state.get_status(job_id)
            if status is not None and status.is_terminal():
                break
            time.sleep(0.5)
        assert state.get_status(job_id) is ManagedJobStatus.SUCCEEDED


def test_pipeline_yaml_header_doc_names_dag(tmp_path):
    """A first document with only `name:` names the pipeline (reference
    convention) instead of becoming a phantom no-op task."""
    from skypilot_tpu.utils import dag_utils
    p = tmp_path / 'pipe.yaml'
    p.write_text('name: my-pipe\n---\nname: a\nrun: echo a\n---\n'
                 'name: b\nrun: echo b\n')
    dag = dag_utils.load_chain_dag_from_yaml(str(p))
    assert dag.name == 'my-pipe'
    assert [t.name for t in dag.tasks] == ['a', 'b']
    # A single-doc YAML whose only key is name still loads as a task.
    p2 = tmp_path / 'single.yaml'
    p2.write_text('name: solo\n')
    dag2 = dag_utils.load_chain_dag_from_yaml(str(p2))
    assert [t.name for t in dag2.tasks] == ['solo']


def test_chain_dump_load_round_trip_preserves_all_tasks(tmp_path):
    """Round trip keeps the DAG name and every task — including a
    name-only first task that would otherwise be mistaken for the
    pipeline header."""
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.utils import dag_utils
    dag = dag_lib.Dag('pipe')
    gate = task_lib.Task(name='gate')  # serializes to name-only
    train = task_lib.Task(name='train', run='echo t')
    dag.add(gate)
    dag.add(train)
    dag.add_edge(gate, train)
    p = tmp_path / 'round.yaml'
    dag_utils.dump_chain_dag_to_yaml(dag, str(p))
    loaded = dag_utils.load_chain_dag_from_yaml(str(p))
    assert loaded.name == 'pipe'
    assert [t.name for t in loaded.tasks] == ['gate', 'train']


def test_empty_dag_dump_load_round_trip(tmp_path):
    """An empty DAG dumps to an empty file and reloads as an empty DAG
    (a lone header doc would reload as a task config and crash)."""
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu.utils import dag_utils
    p = tmp_path / 'empty.yaml'
    dag_utils.dump_chain_dag_to_yaml(dag_lib.Dag('nothing'), str(p))
    loaded = dag_utils.load_chain_dag_from_yaml(str(p))
    assert loaded.tasks == []
