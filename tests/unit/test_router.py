"""Router tests: role dispatch, prefix affinity, same-role failover,
least-loaded selection — plus the LB's HTTP-level 429 retry path
(ISSUE 8 satellite: retry once on an alternate same-role replica
instead of relaying backpressure to the client)."""
from __future__ import annotations

import http.server
import json
import threading
import time

import pytest
import requests

from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import router as router_lib


def _endpoints(*specs):
    return [router_lib.ReplicaEndpoint(**s) for s in specs]


class TestRouterRoles:

    def test_short_prompt_goes_to_decode_pool(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://p', 'role': 'prefill'},
            {'url': 'http://d', 'role': 'decode'}))
        decision = router.route(None, prompt_len=8)
        assert decision.url == 'http://d'
        assert decision.role == 'decode'
        assert decision.handoff_source is None

    def test_long_prompt_gets_prefill_handoff_source(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://p', 'role': 'prefill'},
            {'url': 'http://d', 'role': 'decode', 'page_size': 8}))
        decision = router.route(None, prompt_len=128)
        assert decision.url == 'http://d'
        assert decision.handoff_source == 'http://p'
        assert decision.page_size == 8

    def test_no_handoff_without_prefill_pool(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://a'}, {'url': 'http://b'}))
        decision = router.route(None, prompt_len=128)
        assert decision.url in ('http://a', 'http://b')
        assert decision.handoff_source is None

    def test_decode_pool_falls_back_to_mixed_then_any(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://p', 'role': 'prefill'},
            {'url': 'http://m', 'role': 'mixed'}))
        assert router.route(None, 8).url == 'http://m'
        # Prefill-only fleet still serves rather than 503.
        router.set_endpoints(_endpoints(
            {'url': 'http://p', 'role': 'prefill'}))
        decision = router.route(None, 128)
        assert decision.url == 'http://p'
        assert decision.handoff_source is None  # target IS prefill

    def test_least_loaded_within_pool(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://d1', 'role': 'decode'},
            {'url': 'http://d2', 'role': 'decode'}))
        router.acquire('http://d1')
        assert router.route(None, 8).url == 'http://d2'
        router.acquire('http://d2')
        router.acquire('http://d2')
        assert router.route(None, 8).url == 'http://d1'
        router.release('http://d2')
        router.release('http://d2')
        router.release('http://d2')  # over-release never goes negative
        assert router.route(None, 8).url == 'http://d2'

    def test_controller_load_breaks_ties(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://d1', 'role': 'decode', 'load': 0.9},
            {'url': 'http://d2', 'role': 'decode', 'load': 0.1}))
        assert router.route(None, 8).url == 'http://d2'

    def test_no_replicas_routes_none(self):
        router = router_lib.Router(threshold=64)
        assert router.route(None, 8).url is None


class TestRouterAffinity:

    def test_prefix_affinity_sticks_across_requests(self):
        router = router_lib.Router(threshold=1000)
        router.set_endpoints(_endpoints(
            {'url': 'http://d1', 'role': 'decode'},
            {'url': 'http://d2', 'role': 'decode'}))
        key = router_lib.prompt_key(prompt_ids=[1, 2, 3])
        first = router.route(key, 8)
        assert first.affinity == 'miss'
        router.record_affinity(key, first.url)
        # Load the pinned replica: affinity must still win.
        router.acquire(first.url)
        router.acquire(first.url)
        again = router.route(key, 8)
        assert again.affinity == 'hit'
        assert again.url == first.url
        # A different prefix spreads by load as usual.
        other = router.route(router_lib.prompt_key(
            prompt_ids=[9, 9, 9]), 8)
        assert other.url != first.url

    def test_affinity_reroutes_when_pinned_replica_dies(self):
        router = router_lib.Router(threshold=1000)
        router.set_endpoints(_endpoints(
            {'url': 'http://d1', 'role': 'decode'},
            {'url': 'http://d2', 'role': 'decode'}))
        key = router_lib.prompt_key(prompt_ids=[5, 6, 7])
        router.record_affinity(key, 'http://d1')
        router.set_endpoints(_endpoints(
            {'url': 'http://d2', 'role': 'decode'}))
        decision = router.route(key, 8)
        assert decision.url == 'http://d2'
        assert decision.affinity == 'miss'

    def test_affinity_capacity_bounded(self):
        router = router_lib.Router(threshold=1000, affinity_capacity=2)
        router.set_endpoints(_endpoints(
            {'url': 'http://d', 'role': 'decode'}))
        keys = [router_lib.prompt_key(prompt_ids=[i]) for i in range(3)]
        for key in keys:
            router.record_affinity(key, 'http://d')
        assert router.affinity_target(keys[0]) is None  # LRU-evicted
        assert router.affinity_target(keys[2]) == 'http://d'

    def test_prompt_key_bounded_and_distinct(self):
        long_a = router_lib.prompt_key(prompt_ids=list(range(500)))
        long_b = router_lib.prompt_key(
            prompt_ids=list(range(500)) + [7])
        assert long_a == long_b  # same head
        assert router_lib.prompt_key(prompt_ids=[1]) != \
            router_lib.prompt_key(prompt_ids=[2])
        assert router_lib.prompt_key(text='hello') == \
            router_lib.prompt_key(text='hello')
        assert router_lib.prompt_key() is None

    def test_alternates_same_role_only(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://d1', 'role': 'decode'},
            {'url': 'http://d2', 'role': 'decode'},
            {'url': 'http://p', 'role': 'prefill'}))
        assert router.alternates('http://d1') == ['http://d2']
        assert router.alternates('http://d1',
                                 exclude=['http://d2']) == []

    def test_ensure_urls_keeps_roles_for_known(self):
        router = router_lib.Router(threshold=64)
        router.set_endpoints(_endpoints(
            {'url': 'http://p', 'role': 'prefill'}))
        router.ensure_urls(['http://p', 'http://new'])
        roles = {e.url: e.role for e in router.endpoints()}
        assert roles == {'http://p': 'prefill', 'http://new': 'mixed'}


class _Replica(http.server.ThreadingHTTPServer):
    """Scripted replica: answers /generate per the queued behaviors
    ('ok' or 'busy' -> 429 + Retry-After)."""

    def __init__(self, behaviors):
        super().__init__(('127.0.0.1', 0), _Handler)
        self.behaviors = list(behaviors)
        self.hits = 0

    @property
    def url(self):
        return f'http://127.0.0.1:{self.server_address[1]}'


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):
        del args

    def do_POST(self):
        length = int(self.headers.get('Content-Length', 0))
        self.rfile.read(length)
        server = self.server
        server.hits += 1
        behavior = (server.behaviors.pop(0) if server.behaviors
                    else 'ok')
        if behavior == 'busy':
            body = json.dumps(
                {'error': 'KV page pool exhausted '
                          '(pages_exhausted); retry later'}).encode()
            self.send_response(429)
            self.send_header('Retry-After', '0')
        else:
            body = json.dumps({'tokens': [[1, 2]],
                               'port': server.server_address[1],
                               'role': self.headers.get(
                                   'X-SkyTPU-Routed-Role'),
                               'affinity': self.headers.get(
                                   'X-SkyTPU-Affinity')}).encode()
            self.send_response(200)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def replica_pair():
    servers = [_Replica([]), _Replica([])]
    for server in servers:
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
    yield servers
    for server in servers:
        server.shutdown()


def _start_lb(replicas, **router_kw):
    balancer = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1',
        router=router_lib.Router(**router_kw))
    balancer.set_replicas(replicas)
    port = balancer.start()
    return balancer, port


class TestLbRetryPath:

    def test_429_retries_once_on_same_role_sibling(self, replica_pair):
        first, second = replica_pair
        first.behaviors.append('busy')
        balancer, port = _start_lb(
            [{'url': first.url, 'role': 'decode'},
             {'url': second.url, 'role': 'decode'}],
            threshold=1000)
        try:
            # Pin the first replica via affinity so the 429 provably
            # comes from it, then the retry lands on the sibling.
            balancer.router.record_affinity(
                router_lib.prompt_key(prompt_ids=[1, 2, 3]), first.url)
            resp = requests.post(
                f'http://127.0.0.1:{port}/generate',
                json={'prompt_ids': [[1, 2, 3]],
                      'max_new_tokens': 2}, timeout=10)
            assert resp.status_code == 200
            assert resp.json()['port'] == second.server_address[1]
            assert first.hits == 1 and second.hits == 1
        finally:
            balancer.stop()

    def test_429_relayed_when_no_alternate(self, replica_pair):
        first, _ = replica_pair
        first.behaviors.extend(['busy', 'busy'])
        balancer, port = _start_lb(
            [{'url': first.url, 'role': 'decode'}], threshold=1000)
        try:
            resp = requests.post(
                f'http://127.0.0.1:{port}/generate',
                json={'prompt_ids': [[1, 2, 3]],
                      'max_new_tokens': 2}, timeout=10)
            assert resp.status_code == 429
            assert resp.headers.get('Retry-After') is not None
        finally:
            balancer.stop()

    def test_dead_replica_fails_over_with_buffered_body(
            self, replica_pair):
        _, second = replica_pair
        balancer, port = _start_lb(
            [{'url': 'http://127.0.0.1:9', 'role': 'decode'},
             {'url': second.url, 'role': 'decode'}], threshold=1000)
        try:
            balancer.router.record_affinity(
                router_lib.prompt_key(prompt_ids=[1]),
                'http://127.0.0.1:9')
            resp = requests.post(
                f'http://127.0.0.1:{port}/generate',
                json={'prompt_ids': [[1]], 'max_new_tokens': 2},
                timeout=10)
            assert resp.status_code == 200
            assert resp.json()['port'] == second.server_address[1]
        finally:
            balancer.stop()

    def test_routed_role_and_affinity_headers_forwarded(
            self, replica_pair):
        first, _ = replica_pair
        balancer, port = _start_lb(
            [{'url': first.url, 'role': 'decode'}], threshold=1000)
        try:
            url = f'http://127.0.0.1:{port}/generate'
            body = {'prompt_ids': [[4, 5, 6]], 'max_new_tokens': 2}
            one = requests.post(url, json=body, timeout=10).json()
            assert one['role'] == 'decode'
            assert one['affinity'] == 'miss'
            # The pin is recorded after the LB sees upstream EOF,
            # which can land a beat AFTER the client has the full
            # response — wait for it instead of racing it.
            key = router_lib.prompt_key(prompt_ids=[4, 5, 6])
            deadline = time.time() + 5
            while (balancer.router.affinity_target(key) is None and
                   time.time() < deadline):
                time.sleep(0.02)
            assert balancer.router.affinity_target(key) is not None
            two = requests.post(url, json=body, timeout=10).json()
            assert two['affinity'] == 'hit'
        finally:
            balancer.stop()

    def test_unparseable_body_still_routes(self, replica_pair):
        first, _ = replica_pair
        balancer, port = _start_lb(
            [{'url': first.url, 'role': 'decode'}], threshold=1000)
        try:
            resp = requests.post(
                f'http://127.0.0.1:{port}/generate',
                data=b'this is not json', timeout=10)
            assert resp.status_code == 200
        finally:
            balancer.stop()
