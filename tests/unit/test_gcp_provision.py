"""GCP TPU-VM provisioner tests against a faked TPU REST API.

The injectable transport (tpu_api.set_session_factory) is the hermetic
seam the reference lacks (SURVEY.md §4: "no mocked/fake cloud
provisioner" — fixed here).
"""
from __future__ import annotations

import json
import re
from typing import Dict

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common as pcommon
from skypilot_tpu.provision.gcp import instance as gcp_instance
from skypilot_tpu.provision.gcp import tpu_api
from skypilot_tpu.status_lib import ClusterStatus


class _Resp:

    def __init__(self, status_code=200, payload=None):
        self.status_code = status_code
        self._payload = payload if payload is not None else {}
        self.content = json.dumps(self._payload).encode()
        self.text = json.dumps(self._payload)

    def json(self):
        return self._payload


class FakeTpuService:
    """In-memory TPU API: nodes + queued resources + instant LROs."""

    def __init__(self):
        self.nodes: Dict[str, dict] = {}        # 'zone/node_id' -> node
        self.queued: Dict[str, dict] = {}
        self.create_calls = []
        self.deleted = []
        # Test hooks
        self.fail_create_with = None            # GcpApiError status to raise
        self.qr_states = None                   # iterator of QR states

    # requests.Session interface ------------------------------------

    def request(self, method, url, json=None, params=None, headers=None,
                timeout=None):
        del headers, timeout
        path = url.replace(tpu_api.TPU_API + '/', '')
        m = re.match(
            r'projects/(?P<proj>[^/]+)/locations/(?P<zone>[^/]+)'
            r'(?P<rest>/.*)?$', path)
        assert m, path
        zone, rest = m.group('zone'), m.group('rest') or ''
        if rest.startswith('/nodes'):
            return self._nodes(method, zone, rest, json, params)
        if rest.startswith('/queuedResources'):
            return self._queued(method, zone, rest, json, params)
        raise AssertionError(f'unhandled path {path}')

    def _nodes(self, method, zone, rest, body, params):
        if rest == '/nodes' and method == 'POST':
            node_id = params['nodeId']
            if self.fail_create_with:
                status = self.fail_create_with
                self.fail_create_with = None
                return _Resp(status,
                             {'error': {'message': 'no more capacity'}})
            self.create_calls.append((zone, node_id, body))
            node = dict(body)
            node['state'] = 'READY'
            node.setdefault('networkEndpoints', [
                {'ipAddress': '10.0.0.1',
                 'accessConfig': {'externalIp': '34.1.2.3'}},
            ])
            self.nodes[f'{zone}/{node_id}'] = node
            return _Resp(200, {'name': 'op/create', 'done': True})
        m = re.match(r'/nodes/(?P<nid>[^:/]+)(?P<verb>:\w+)?$', rest)
        assert m, rest
        key = f'{zone}/{m.group("nid")}'
        node = self.nodes.get(key)
        verb = m.group('verb')
        if method == 'GET':
            if node is None:
                return _Resp(404, {'error': {'message': 'not found'}})
            return _Resp(200, node)
        if method == 'DELETE':
            if node is None:
                return _Resp(404, {'error': {'message': 'not found'}})
            self.deleted.append(key)
            del self.nodes[key]
            return _Resp(200, {'name': 'op/delete', 'done': True})
        if verb == ':stop':
            node['state'] = 'STOPPED'
            return _Resp(200, {'name': 'op/stop', 'done': True})
        if verb == ':start':
            node['state'] = 'READY'
            return _Resp(200, {'name': 'op/start', 'done': True})
        raise AssertionError(f'unhandled {method} {rest}')

    def _queued(self, method, zone, rest, body, params):
        if rest == '/queuedResources' and method == 'POST':
            qr_id = params['queuedResourceId']
            self.queued[f'{zone}/{qr_id}'] = {
                'body': body,
                'state': {'state': 'WAITING_FOR_RESOURCES'},
            }
            return _Resp(200, {'name': 'op/qr', 'done': True})
        m = re.match(r'/queuedResources/(?P<qid>[^:/]+)$', rest)
        assert m, rest
        key = f'{zone}/{m.group("qid")}'
        qr = self.queued.get(key)
        if method == 'GET':
            if qr is None:
                return _Resp(404, {'error': {'message': 'not found'}})
            if self.qr_states is not None:
                try:
                    qr['state'] = {'state': next(self.qr_states)}
                except StopIteration:
                    pass
            if qr['state']['state'] == 'ACTIVE':
                # Fulfilment: materialize the requested nodes.
                for spec in qr['body']['tpu']['nodeSpec']:
                    node = dict(spec['node'])
                    node['state'] = 'READY'
                    node.setdefault('networkEndpoints', [
                        {'ipAddress': '10.0.0.9',
                         'accessConfig': {'externalIp': '34.9.9.9'}}])
                    self.nodes[f'{zone}/{spec["nodeId"]}'] = node
            return _Resp(200, qr)
        if method == 'DELETE':
            if qr is None:
                return _Resp(404, {'error': {'message': 'not found'}})
            del self.queued[key]
            return _Resp(200, {'name': 'op/qrdel', 'done': True})
        raise AssertionError(f'unhandled {method} {rest}')


@pytest.fixture()
def fake_api(monkeypatch):
    service = FakeTpuService()
    monkeypatch.setattr(tpu_api, '_session_factory', lambda: service)
    monkeypatch.setattr(tpu_api, '_gcloud_token', lambda: 'fake-token')
    monkeypatch.setenv('SKYTPU_GCP_PROJECT', 'test-proj')
    yield service


def _config(cluster='tc1', mode='on_demand', num_slices=1,
            accel='v5litepod-8', hosts=2):
    return pcommon.ProvisionConfig(
        provider_name='gcp', cluster_name=cluster, region='us-central2',
        zones=['us-central2-b'],
        deploy_vars={
            'tpu': True,
            'tpu_accelerator_type': accel,
            'tpu_runtime_version': 'tpu-ubuntu2204-base',
            'tpu_num_hosts': hosts,
            'provision_mode': mode,
            'num_slices': num_slices,
            'use_spot': mode == 'spot',
            'labels': {'team': 'ml'},
        })


@pytest.fixture(autouse=True)
def _fake_keys(monkeypatch):
    monkeypatch.setattr(
        'skypilot_tpu.authentication.gcp_ssh_metadata',
        lambda ssh_user='skytpu': f'{ssh_user}:ssh-ed25519 FAKEKEY')
    monkeypatch.setattr(
        'skypilot_tpu.authentication.get_or_generate_keys',
        lambda: ('/fake/key', '/fake/key.pub'))


class TestOnDemand:

    def test_create_and_info(self, fake_api):
        record = gcp_instance.run_instances(_config())
        assert record.created_instance_ids == ['tc1']
        assert not record.waiting
        zone, node_id, body = fake_api.create_calls[0]
        assert zone == 'us-central2-b'
        assert body['acceleratorType'] == 'v5litepod-8'
        assert body['labels']['skytpu-cluster'] == 'tc1'
        assert 'ssh-keys' in body['metadata']
        assert 'schedulingConfig' not in body

        gcp_instance.wait_instances('tc1')
        info = gcp_instance.get_cluster_info('tc1')
        assert info.num_hosts == 1
        assert info.instances[0].external_ip == '34.1.2.3'
        assert info.ssh_user == 'skytpu'

        statuses = gcp_instance.query_instances('tc1')
        assert statuses == {'tc1': ClusterStatus.UP}

    def test_idempotent_rerun(self, fake_api):
        gcp_instance.run_instances(_config())
        record = gcp_instance.run_instances(_config())
        assert record.created_instance_ids == []
        assert len(fake_api.create_calls) == 1

    def test_stop_start_single_host(self, fake_api):
        gcp_instance.run_instances(_config(hosts=1))
        gcp_instance.stop_instances('tc1')
        assert gcp_instance.query_instances('tc1') == {
            'tc1': ClusterStatus.STOPPED}
        record = gcp_instance.run_instances(_config(hosts=1))
        assert record.resumed_instance_ids == ['tc1']
        assert gcp_instance.query_instances('tc1') == {
            'tc1': ClusterStatus.UP}

    def test_multihost_stop_rejected(self, fake_api):
        gcp_instance.run_instances(_config(hosts=4))
        gcp_instance.get_cluster_info('tc1')  # records num_hosts
        with pytest.raises(exceptions.NotSupportedError):
            gcp_instance.stop_instances('tc1')

    def test_terminate(self, fake_api):
        gcp_instance.run_instances(_config())
        gcp_instance.terminate_instances('tc1')
        assert fake_api.deleted == ['us-central2-b/tc1']
        assert gcp_instance.query_instances('tc1') == {}
        # idempotent
        gcp_instance.terminate_instances('tc1')


class TestSpot:

    def test_spot_scheduling_config(self, fake_api):
        gcp_instance.run_instances(_config(mode='spot'))
        _, _, body = fake_api.create_calls[0]
        assert body['schedulingConfig']['preemptible'] is True

    def test_preempted_node_deleted_then_recreated(self, fake_api):
        gcp_instance.run_instances(_config(mode='spot'))
        fake_api.nodes['us-central2-b/tc1']['state'] = 'PREEMPTED'
        assert gcp_instance.query_instances('tc1') == {'tc1': None}
        record = gcp_instance.run_instances(_config(mode='spot'))
        assert record.created_instance_ids == ['tc1']
        assert fake_api.deleted == ['us-central2-b/tc1']
        assert len(fake_api.create_calls) == 2


class TestMultislice:

    def test_two_slices_two_nodes(self, fake_api):
        record = gcp_instance.run_instances(_config(num_slices=2))
        assert record.created_instance_ids == ['tc1-0', 'tc1-1']
        info = gcp_instance.get_cluster_info('tc1')
        assert [i.slice_id for i in info.instances] == [0, 1]
        assert info.instances[0].tags['node_id'] == 'tc1-0'


class TestQueuedResources:

    def test_queued_waits_then_fulfils(self, fake_api):
        record = gcp_instance.run_instances(_config(mode='queued'))
        assert record.waiting
        assert record.queued_resource_id == 'tc1'
        assert 'us-central2-b/tc1' in fake_api.queued
        # Capacity not granted yet:
        assert gcp_instance.wait_capacity('tc1', timeout=0) is False
        # Grant it:
        fake_api.queued['us-central2-b/tc1']['state'] = {
            'state': 'ACTIVE'}
        assert gcp_instance.wait_capacity('tc1', timeout=0) is True
        info = gcp_instance.get_cluster_info('tc1')
        assert info.num_hosts == 1

    def test_queued_failure_raises(self, fake_api):
        gcp_instance.run_instances(_config(mode='queued'))
        fake_api.queued['us-central2-b/tc1']['state'] = {
            'state': 'FAILED'}
        with pytest.raises(exceptions.ProvisionError):
            gcp_instance.wait_capacity('tc1', timeout=0)

    def test_terminate_deletes_queued_resource(self, fake_api):
        gcp_instance.run_instances(_config(mode='queued'))
        fake_api.queued['us-central2-b/tc1']['state'] = {
            'state': 'ACTIVE'}
        gcp_instance.wait_capacity('tc1', timeout=0)
        gcp_instance.terminate_instances('tc1')
        assert fake_api.queued == {}


class TestErrors:

    def test_capacity_error_classified(self, fake_api):
        fake_api.fail_create_with = 429
        with pytest.raises(tpu_api.GcpApiError) as err:
            gcp_instance.run_instances(_config())
        assert err.value.is_quota_or_capacity


def test_explicit_topology_overrides_registry_default(enable_all_infra):
    """accelerator_args topology (or the flat YAML spelling) must reach
    the provisioner deploy vars, not be silently dropped."""
    from skypilot_tpu import Resources
    from skypilot_tpu.clouds import registry
    cloud = registry.from_str('gcp')
    # tpu-v5p-32 counts cores: 16 chips; 4x2x2 is a valid non-default
    # 16-chip torus.
    resources = Resources.from_yaml_config({
        'cloud': 'gcp', 'accelerators': 'tpu-v5p-32',
        'topology': '4x2x2'})
    region = cloud.regions_with_offering(resources)[0]
    deploy = cloud.make_deploy_resources_variables(
        resources, 'c1', region, region.zones)
    assert deploy['tpu_topology'] == '4x2x2'
    default = cloud.make_deploy_resources_variables(
        Resources(cloud='gcp', accelerators='tpu-v5p-32'),
        'c2', region, region.zones)
    assert default['tpu_topology'] != '4x2x2'
    # A topology whose chip product mismatches the slice is rejected
    # at validation time, not deep in provisioning.
    import pytest as _pytest
    bad = Resources.from_yaml_config({
        'cloud': 'gcp', 'accelerators': 'tpu-v5p-32',
        'topology': '2x4x4'})  # 32 chips != 16
    with _pytest.raises(ValueError, match='16-chip'):
        cloud.make_deploy_resources_variables(bad, 'c3', region,
                                              region.zones)
