"""Spec-layer tests: accelerator registry, catalog, resources, task, dag.

Mirrors the reference's offline test strategy (tests/unit_tests/
test_resources.py, test_yaml_parser.py, test_list_accelerators.py) — all
hermetic, no cloud access.
"""
from __future__ import annotations

import textwrap

import pytest

from skypilot_tpu import catalog
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.clouds import registry
from skypilot_tpu.utils import accelerator_registry as ar


# ------------------------------------------------------ accelerator registry


def test_parse_tpu_names():
    spec = ar.parse_tpu_name('tpu-v5p-64')
    assert spec is not None
    assert spec.generation == 'v5p'
    assert spec.num_chips == 32          # v5p counts TensorCores
    assert spec.num_hosts == 8           # 4 chips per host
    assert spec.chips_per_host == 4

    v5e = ar.parse_tpu_name('tpu-v5e-16')
    assert v5e.num_chips == 16           # v5e counts chips
    assert v5e.num_hosts == 4

    single = ar.parse_tpu_name('tpu-v5e-8')
    assert single.num_hosts == 1         # single host up to 8 chips
    assert not single.is_pod

    v4 = ar.parse_tpu_name('tpu-v4-8')
    assert v4.num_chips == 4
    assert v4.num_hosts == 1

    assert ar.parse_tpu_name('A100') is None
    assert ar.parse_tpu_name('tpu-v9-8') is None


def test_topology_is_consistent():
    for name in ar.list_tpu_names(256):
        spec = ar.parse_tpu_name(name)
        product = 1
        for d in spec.topology:
            product *= d
        assert product == spec.num_chips, name


def test_canonicalize():
    assert ar.canonicalize_accelerator_name('TPU-V5P-64') == 'tpu-v5p-64'
    assert ar.canonicalize_accelerator_name('tpu-v5litepod-8') == 'tpu-v5e-8'
    assert ar.canonicalize_accelerator_name('v5e-16') == 'tpu-v5e-16'
    assert ar.canonicalize_accelerator_name('a100') == 'A100'
    assert ar.is_schedulable_non_gpu_accelerator('tpu-v4-8')
    assert not ar.is_schedulable_non_gpu_accelerator('A100')


# ------------------------------------------------------------------ catalog


def test_tpu_hourly_cost():
    cost = catalog.get_tpu_hourly_cost('gcp', 'tpu-v5e-16')
    assert cost == pytest.approx(1.2 * 16)
    spot = catalog.get_tpu_hourly_cost('gcp', 'tpu-v5e-16', use_spot=True)
    assert spot < cost
    with pytest.raises(exceptions.ResourcesUnavailableError):
        catalog.get_tpu_hourly_cost('gcp', 'tpu-v5e-16', region='us-central1')


def test_gpu_instance_lookup():
    types = catalog.get_instance_type_for_accelerator('gcp', 'A100', 8)
    assert types == ['a2-highgpu-8g']
    assert catalog.get_instance_type_for_accelerator('gcp', 'A100', 3) is None
    cpus, mem = catalog.get_vcpus_mem_from_instance_type('gcp', 'a2-highgpu-8g')
    assert cpus == 96 and mem == 680


def test_default_instance_type():
    assert catalog.get_default_instance_type('gcp') == 'n2-standard-8'
    assert catalog.get_default_instance_type('gcp', cpus='16+') == 'n2-standard-16'


def test_validate_region_zone():
    region, zone = catalog.validate_region_zone('gcp', None, 'us-central2-b')
    assert region == 'us-central2'
    with pytest.raises(ValueError):
        catalog.validate_region_zone('gcp', 'nowhere', None)


def test_list_accelerators_filter():
    accs = catalog.list_accelerators(name_filter='v5p')
    assert any('tpu-v5p' in name for name in accs)
    offering = accs['tpu-v5p-8'][0]
    assert offering.num_hosts == 1
    assert offering.price == pytest.approx(4.2 * 4)


# ---------------------------------------------------------------- resources


def test_resources_tpu_grammar():
    r = resources_lib.Resources(accelerators='tpu-v5p-64')
    assert r.tpu_spec is not None
    assert r.num_hosts == 8
    assert not r.use_spot

    r2 = resources_lib.Resources(accelerators='tpu-v5e-16', capacity='spot')
    assert r2.use_spot
    assert r2.provision_mode is cloud_lib.ProvisionMode.SPOT

    r3 = resources_lib.Resources(accelerators='tpu-v5e-16', num_slices=4)
    assert r3.num_hosts == 16


def test_resources_invalid():
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(accelerators='tpu-v5e-16',
                                instance_type='n2-standard-8')
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(accelerators='A100:8', num_slices=2)
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(accelerators='tpu-v5e-16', capacity='reserved')
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(use_spot=True, capacity='on_demand')


def test_resources_cost():
    r = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    assert r.get_cost(3600) == pytest.approx(1.2 * 8)
    vm = resources_lib.Resources(cloud='gcp', instance_type='a2-highgpu-8g')
    assert vm.get_cost(3600) == pytest.approx(29.3864)


def test_resources_reuse_check():
    small = resources_lib.Resources(accelerators='tpu-v5e-8')
    big = resources_lib.Resources(accelerators='tpu-v5e-16')
    assert not big.less_demanding_than(small)
    same = resources_lib.Resources(accelerators='tpu-v5e-8')
    assert same.less_demanding_than(small)


def test_resources_yaml_round_trip():
    r = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5p-32',
                                capacity='queued', region='us-east5',
                                labels={'team': 'ml'})
    r2 = resources_lib.Resources.from_yaml_config(r.to_yaml_config())
    assert r == r2
    assert r2.provision_mode is cloud_lib.ProvisionMode.QUEUED


def test_gcp_feasibility():
    gcp = registry.from_str('gcp')
    launchable, _ = gcp.get_feasible_launchable_resources(
        resources_lib.Resources(accelerators='tpu-v5e-16'))
    assert len(launchable) == 1
    assert launchable[0].is_launchable()

    launchable, _ = gcp.get_feasible_launchable_resources(
        resources_lib.Resources(accelerators='A100:8'))
    assert launchable[0].instance_type == 'a2-highgpu-8g'

    launchable, fuzzy = gcp.get_feasible_launchable_resources(
        resources_lib.Resources(accelerators='A100:3'))
    assert not launchable and fuzzy


def test_gcp_pod_cannot_stop():
    gcp = registry.from_str('gcp')
    pod = resources_lib.Resources(accelerators='tpu-v5e-16')
    with pytest.raises(exceptions.NotSupportedError):
        type(gcp).check_features_are_supported(
            pod, {cloud_lib.CloudImplementationFeatures.STOP})
    # Single-host slices can stop.
    single = resources_lib.Resources(accelerators='tpu-v5e-8')
    type(gcp).check_features_are_supported(
        single, {cloud_lib.CloudImplementationFeatures.STOP})


# --------------------------------------------------------------- task / dag


def test_task_yaml_round_trip(tmp_path):
    yaml_text = textwrap.dedent("""\
        name: train
        num_nodes: 1
        envs:
          MODEL: llama3-8b
        resources:
          accelerators: tpu-v5p-64
          capacity: spot
        setup: pip install -e .
        run: python train.py --model $MODEL
        """)
    path = tmp_path / 'task.yaml'
    path.write_text(yaml_text)
    task = task_lib.Task.from_yaml(str(path))
    assert task.name == 'train'
    # Declared env vars are substituted into run.
    assert task.run == 'python train.py --model llama3-8b'
    r = next(iter(task.resources))
    assert r.tpu_spec.name == 'tpu-v5p-64'
    assert r.use_spot
    config = task.to_yaml_config()
    task2 = task_lib.Task.from_yaml_config(config)
    assert next(iter(task2.resources)) == r


def test_task_validation():
    with pytest.raises(exceptions.InvalidTaskError):
        task_lib.Task(name='bad name!')
    with pytest.raises(exceptions.InvalidTaskError):
        task_lib.Task(num_nodes=0)
    with pytest.raises(exceptions.InvalidTaskError):
        task_lib.Task(workdir='/nonexistent/dir')


def test_dag_chain():
    with dag_lib.Dag('pipeline') as dag:
        a = task_lib.Task(name='a')
        b = task_lib.Task(name='b')
        c = task_lib.Task(name='c')
        a >> b >> c
    assert dag.is_chain()
    assert dag.topological_order() == [a, b, c]
    d = task_lib.Task(name='d')
    dag.add(d)
    dag.add_edge(a, d)
    assert not dag.is_chain()


def test_local_tpu_feasibility():
    local = registry.from_str('local')
    launchable, _ = local.get_feasible_launchable_resources(
        resources_lib.Resources(accelerators='tpu-v5e-16'))
    assert launchable[0].is_launchable()
    assert launchable[0].instance_type is None
    region = local.regions_with_offering(launchable[0])[0]
    vars_ = local.make_deploy_resources_variables(launchable[0], 'c', region,
                                                  region.zones)
    assert vars_['tpu_num_hosts'] == 4


def test_resources_hash_eq_consistent():
    a = resources_lib.Resources(labels={'a': '1', 'b': '2'})
    b = resources_lib.Resources(labels={'b': '2', 'a': '1'})
    assert a == b
    assert hash(a) == hash(b)
    assert len({a, b}) == 1


def test_invalid_tpu_and_count_strings():
    assert ar.parse_tpu_name('tpu-v5p-3') is None  # partial chip
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(cpus='4cores')
    with pytest.raises(exceptions.InvalidTaskError):
        resources_lib.Resources(memory='lots+')
    assert resources_lib.Resources(cpus=4).cpus == '4'
    assert resources_lib.Resources(memory='16+').memory == '16+'


class TestResourcesYamlAliases:
    """Reference-familiar YAML spellings normalize onto canonical
    fields (docs/migration.md documents these)."""

    def test_infra_capacity_type_spot_recovery(self):
        from skypilot_tpu.resources import Resources
        r = Resources.from_yaml_config({
            'infra': 'gcp', 'accelerators': 'tpu-v5e-8',
            'capacity_type': 'spot', 'spot_recovery': 'FAILOVER'})
        assert r.cloud is not None and r.cloud.name == 'gcp'
        assert r.use_spot
        assert r.job_recovery == 'FAILOVER'

    def test_flat_tpu_args_fold_into_accelerator_args(self):
        from skypilot_tpu.resources import Resources
        r = Resources.from_yaml_config({
            'accelerators': 'tpu-v5p-16', 'topology': '2x2x4',
            'runtime_version': 'v2-alpha',
            'accelerator_args': {'reservation': 'res-1'}})
        assert r.accelerator_args == {'topology': '2x2x4',
                                      'runtime_version': 'v2-alpha',
                                      'reservation': 'res-1'}

    def test_alias_conflict_rejected(self):
        import pytest as _pytest
        from skypilot_tpu import exceptions
        from skypilot_tpu.resources import Resources
        with _pytest.raises(exceptions.InvalidTaskError,
                            match='not both'):
            Resources.from_yaml_config({'cloud': 'gcp', 'infra': 'aws'})
