"""Docker cloud + provisioner (reference local_docker_backend parity,
VERDICT inventory row #12).  docker CLI behind an injectable runner."""
from __future__ import annotations

import json

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import registry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.docker import instance as docker_instance
from skypilot_tpu.utils import command_runner


class FakeDockerCli:
    """Container state machine keyed on docker CLI argv."""

    def __init__(self):
        self.containers = {}  # name -> {'labels': {...}, 'status': str}
        self.calls = []

    def __call__(self, argv):
        self.calls.append(argv)
        cmd = argv[1]
        if cmd == 'run':
            name = argv[argv.index('--name') + 1]
            labels = {}
            for i, a in enumerate(argv):
                if a == '--label':
                    k, v = argv[i + 1].split('=', 1)
                    labels[k] = v
            image = argv[-3]
            self.containers[name] = {'labels': labels, 'status': 'Up',
                                     'image': image}
            return 0, name + '\n', ''
        if cmd == 'ps':
            label_filter = next(a for a in argv if a.startswith('label='))
            _, kv = label_filter.split('=', 1)
            key, value = kv.split('=', 1)
            include_stopped = '-a' in argv
            rows = []
            for name, c in self.containers.items():
                if c['labels'].get(key) != value:
                    continue
                if not include_stopped and c['status'] != 'Up':
                    continue
                status = ('Up 5 minutes' if c['status'] == 'Up'
                          else 'Exited (0) 1 minute ago')
                rows.append(json.dumps({'Names': name, 'Status': status}))
            return 0, '\n'.join(rows) + '\n', ''
        if cmd == 'stop':
            self.containers[argv[2]]['status'] = 'Exited'
            return 0, '', ''
        if cmd == 'start':
            self.containers[argv[2]]['status'] = 'Up'
            return 0, '', ''
        if cmd == 'rm':
            self.containers.pop(argv[-1], None)
            return 0, '', ''
        return 1, '', f'unhandled docker {cmd}'


@pytest.fixture
def fake_docker():
    cli = FakeDockerCli()
    docker_instance.set_cli_runner(cli)
    yield cli
    docker_instance.set_cli_runner(None)


def _config(cluster='dkr', count=2, image=None):
    return provision_common.ProvisionConfig(
        provider_name='docker', cluster_name=cluster, region='docker',
        zones=['docker'], deploy_vars={'image_id': image}, count=count)


class TestDockerProvisioner:

    def test_lifecycle(self, fake_docker):
        record = docker_instance.run_instances(_config())
        assert record.created_instance_ids == ['skytpu-dkr-0',
                                               'skytpu-dkr-1']
        assert record.head_instance_id == 'skytpu-dkr-0'
        status = docker_instance.query_instances('dkr')
        assert all(s.value == 'UP' for s in status.values())

        info = docker_instance.get_cluster_info('dkr')
        assert [i.instance_id for i in info.instances] == [
            'skytpu-dkr-0', 'skytpu-dkr-1']
        runners = docker_instance.get_command_runners(info)
        assert isinstance(runners[0],
                          command_runner.DockerCommandRunner)
        argv = runners[0]._exec_argv('echo hi')
        assert argv[:2] == ['docker', 'exec']
        assert 'skytpu-dkr-0' in argv

        docker_instance.stop_instances('dkr')
        status = docker_instance.query_instances('dkr')
        assert all(s.value == 'STOPPED' for s in status.values())

        record = docker_instance.run_instances(_config())
        assert len(record.resumed_instance_ids) == 2

        docker_instance.terminate_instances('dkr')
        assert docker_instance.query_instances('dkr') == {}

    def test_custom_image(self, fake_docker):
        docker_instance.run_instances(_config(image='myimage:1'))
        assert fake_docker.containers['skytpu-dkr-0']['image'] == \
            'myimage:1'

    def test_default_image(self, fake_docker):
        docker_instance.run_instances(_config())
        assert fake_docker.containers['skytpu-dkr-0']['image'] == \
            docker_instance.DEFAULT_IMAGE

    def test_count_mismatch(self, fake_docker):
        docker_instance.run_instances(_config(count=1))
        with pytest.raises(exceptions.ResourcesMismatchError):
            docker_instance.run_instances(_config(count=2))

    def test_worker_only_preserves_head(self, fake_docker):
        docker_instance.run_instances(_config(count=3))
        docker_instance.terminate_instances('dkr', worker_only=True)
        assert list(docker_instance.query_instances('dkr')) == [
            'skytpu-dkr-0']


class TestDockerCloud:

    def test_registered_and_feasible(self):
        cloud = registry.CLOUD_REGISTRY['docker']
        r = sky.Resources(cloud='docker')
        launchable, _ = cloud.get_feasible_launchable_resources(r)
        assert launchable and launchable[0].instance_type == 'docker'

    def test_no_tpus_in_containers(self):
        cloud = registry.CLOUD_REGISTRY['docker']
        r = sky.Resources(accelerators='tpu-v5e-8')
        launchable, _ = cloud.get_feasible_launchable_resources(r)
        assert launchable == []
