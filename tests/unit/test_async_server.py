"""Asyncio model-server front end (serve/async_server).

The VERDICT r4 'done' bar: N concurrent SSE streams + health probes
served from ONE event loop (no thread per connection), with the same
endpoint surface and generation results as the threaded front.
"""
from __future__ import annotations

import json
import socket
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest
import requests

from skypilot_tpu.models import decode
from skypilot_tpu.serve import async_server, model_server


@pytest.fixture(scope='module')
def cb_server():
    """Continuous-batching server behind the async front."""
    srv = model_server.ModelServer('tiny', max_len=64, max_batch=4,
                                   continuous_batching=True)
    port, shutdown = async_server.start_background(srv)
    yield srv, port
    shutdown()
    srv.close()


def test_health_and_engine_stats(cb_server):
    _, port = cb_server
    resp = requests.get(f'http://127.0.0.1:{port}/', timeout=10)
    assert resp.status_code == 200
    body = resp.json()
    assert body['status'] == 'ok'
    assert body['engine']['slots'] == 4


def test_generate_parity_with_decode(cb_server):
    srv, port = cb_server
    prompt = [[5, 7, 11, 13]]
    resp = requests.post(
        f'http://127.0.0.1:{port}/generate',
        json={'prompt_ids': prompt, 'max_new_tokens': 6}, timeout=120)
    assert resp.status_code == 200, resp.text
    _, expected = decode.generate(
        srv.cfg, srv.params, jnp.asarray(prompt, jnp.int32),
        max_new_tokens=6, max_len=srv.max_len)
    np.testing.assert_array_equal(
        np.asarray(resp.json()['tokens']), np.asarray(expected))


def test_validation_and_unknown_path(cb_server):
    _, port = cb_server
    assert requests.post(f'http://127.0.0.1:{port}/generate',
                         json={'prompt_ids': [[1] * 60],
                               'max_new_tokens': 30},
                         timeout=30).status_code == 400
    assert requests.post(f'http://127.0.0.1:{port}/nope', json={},
                         timeout=30).status_code == 404
    bad = requests.post(f'http://127.0.0.1:{port}/generate',
                        data=b'{not json', timeout=30)
    assert bad.status_code == 400


def _read_sse(resp):
    events = []
    for line in resp.iter_lines():
        if line.startswith(b'data: '):
            events.append(line[len(b'data: '):].decode())
    return events


def test_concurrent_sse_streams_one_loop(cb_server):
    """More simultaneous SSE streams than engine slots, all served from
    the single event loop; every stream completes with [DONE] and the
    same token sequence as a solo run."""
    srv, port = cb_server
    prompt = [3, 1, 4, 1, 5]
    n_streams = 8  # > 4 slots: some wait queued while others stream

    solo = requests.post(
        f'http://127.0.0.1:{port}/generate',
        json={'prompt_ids': [prompt], 'max_new_tokens': 5},
        timeout=120).json()['tokens'][0]

    results = [None] * n_streams

    def one(i):
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate_stream',
            json={'prompt_ids': prompt, 'max_new_tokens': 5},
            stream=True, timeout=300)
        assert resp.status_code == 200
        events = _read_sse(resp)
        assert events[-1] == '[DONE]'
        results[i] = [json.loads(e)['token'] for e in events[:-1]]

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(n_streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for got in results:
        assert got == solo, (got, solo)


def test_stream_client_disconnect_frees_slot(cb_server):
    """Dropping an SSE connection mid-stream cancels the request: the
    engine's busy slot count returns to zero."""
    srv, port = cb_server
    sock = socket.create_connection(('127.0.0.1', port), timeout=10)
    body = json.dumps({'prompt_ids': [1, 2, 3],
                       'max_new_tokens': 50}).encode()
    sock.sendall(
        b'POST /generate_stream HTTP/1.1\r\n'
        b'Content-Type: application/json\r\n'
        + f'Content-Length: {len(body)}\r\n\r\n'.encode() + body)
    sock.recv(1024)  # wait for the stream to actually start
    sock.close()     # client vanishes mid-generation
    deadline = time.time() + 60
    while time.time() < deadline:
        stats = requests.get(f'http://127.0.0.1:{port}/',
                             timeout=10).json()['engine']
        if stats['busy_slots'] == 0 and stats['queued_requests'] == 0:
            return
        time.sleep(0.5)
    pytest.fail(f'slot leaked after disconnect: {stats}')


def test_generate_text_roundtrip(cb_server):
    """/generate_text (byte tokenizer fallback) + SSE text streaming
    through the async front."""
    _, port = cb_server
    resp = requests.post(
        f'http://127.0.0.1:{port}/generate_text',
        json={'prompt': 'ab', 'max_new_tokens': 4}, timeout=120)
    assert resp.status_code == 200, resp.text
    assert 'completion' in resp.json()

    stream = requests.post(
        f'http://127.0.0.1:{port}/generate_text',
        json={'prompt': 'ab', 'max_new_tokens': 4, 'stream': True},
        stream=True, timeout=120)
    events = _read_sse(stream)
    assert events[-1] == '[DONE]'


def test_keepalive_connection_reuse(cb_server):
    """Multiple requests ride one kept-alive connection."""
    _, port = cb_server
    with requests.Session() as session:
        for _ in range(3):
            assert session.get(f'http://127.0.0.1:{port}/',
                               timeout=10).status_code == 200


def test_lockstep_server_without_engine():
    """The async front also serves a non-continuous-batching server
    (lock-step decode in the executor); streaming is rejected."""
    srv = model_server.ModelServer('tiny', max_len=32, max_batch=2)
    port, shutdown = async_server.start_background(srv)
    try:
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate',
            json={'prompt_ids': [[3, 5]], 'max_new_tokens': 3},
            timeout=120)
        assert resp.status_code == 200, resp.text
        assert requests.post(
            f'http://127.0.0.1:{port}/generate_stream',
            json={'prompt_ids': [3, 5], 'max_new_tokens': 3},
            timeout=30).status_code == 400
    finally:
        shutdown()
        srv.close()


def test_sampling_through_async_front(cb_server):
    """temperature/top_k/seed ride the JSON API into the engine's
    on-device sampler; same seed -> same stream."""
    _, port = cb_server

    def call():
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate',
            json={'prompt_ids': [[2, 4, 6]], 'max_new_tokens': 5,
                  'temperature': 0.8, 'top_k': 8, 'seed': 17},
            timeout=120)
        resp.raise_for_status()
        return resp.json()['tokens'][0]

    first = call()
    assert len(first) == 5
    assert call() == first


def test_async_429_and_retry_after_on_full_queue():
    srv = model_server.ModelServer('tiny', max_len=64, max_batch=1,
                                   continuous_batching=True,
                                   max_queue=1)
    port, shutdown = async_server.start_background(srv)
    try:
        engine = srv._engine  # pylint: disable=protected-access
        blocker = engine.submit([1, 2, 3], 50)
        deadline = time.time() + 30
        while (engine.stats()['busy_slots'] == 0 and
               time.time() < deadline):
            time.sleep(0.01)
        queued = engine.submit([4, 5], 4)
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate',
            json={'prompt_ids': [[6, 7]], 'max_new_tokens': 2},
            timeout=60)
        assert resp.status_code == 429, resp.text
        assert int(resp.headers['Retry-After']) >= 1
        # Streaming submits get the same pushback.
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate_stream',
            json={'prompt_ids': [6, 7], 'max_new_tokens': 2},
            timeout=60)
        assert resp.status_code == 429
        blocker.cancel()
        queued.result(timeout=120)
    finally:
        shutdown()
        srv.close()


def test_role_budget_on_async_front(cb_server):
    """The asyncio front serves the same /role_budget contract as the
    threaded one: a same-role push is a rebalance (applied, NOT a
    morph); bad payloads are 400s."""
    srv, port = cb_server
    url = f'http://127.0.0.1:{port}'
    try:
        resp = requests.post(url + '/role_budget',
                             json={'split': 0.9, 'version': 1},
                             timeout=10)
        assert resp.status_code == 200, resp.text
        body = resp.json()
        assert body['applied'] is True
        assert body['morphed'] is False  # same role: rebalance
        assert body['role'] == srv.role
        assert body['budget']['split'] == 0.9
        assert requests.post(url + '/role_budget',
                             json={'role': 'training'},
                             timeout=10).status_code == 400
    finally:
        # Re-open the shared fixture unclamped for later tests.
        requests.post(url + '/role_budget',
                      json={'split': 0.5, 'version': 2}, timeout=10)
