"""Storage subsystem tests (hermetic — CLI calls are faked).

Parity with the reference's offline storage tests
(/root/reference/tests/test_storage.py approach: no real buckets for
unit-level checks).
"""
from __future__ import annotations

import subprocess

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.data import mounting_utils
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.data import storage_mounting
from skypilot_tpu.data import storage_utils
from skypilot_tpu.data.storage import Storage
from skypilot_tpu.data.storage import StorageMode
from skypilot_tpu.data.storage import StoreType


def _fake_run(history):
    """Fake CLI: bucket-existence probes report 'not found' (rc 1)."""

    def run(cmd, **kwargs):
        history.append(cmd)
        rc = 1 if ('ls' in cmd and '-b' in cmd) or 'head-bucket' in cmd \
            else 0
        return subprocess.CompletedProcess(cmd, rc, stdout='', stderr='')

    return run


class TestStoreType:

    def test_from_url(self):
        assert StoreType.from_url('gs://b/path') is StoreType.GCS
        assert StoreType.from_url('s3://b') is StoreType.S3
        with pytest.raises(ValueError):
            StoreType.from_url('azure://x')


class TestStorage:

    def test_name_from_bucket_url(self):
        s = Storage(source='gs://my-bucket')
        assert s.name == 'my-bucket'
        assert StoreType.GCS in s.stores

    def test_subpath_source_preserved(self):
        s = Storage(source='gs://my-bkt/train-data')
        store = s.stores[StoreType.GCS]
        assert store.url == 'gs://my-bkt/train-data'
        assert '--only-dir train-data' in store.mount_command('/data')

    def test_delete_missing_store_raises(self):
        s = Storage(source='gs://bkt-one')
        with pytest.raises(exceptions.StorageError):
            s.delete(StoreType.S3)

    def test_requires_name_for_local(self, tmp_path):
        with pytest.raises(exceptions.StorageSpecError):
            Storage(source=str(tmp_path))

    def test_local_source_must_exist(self):
        with pytest.raises(exceptions.StorageSourceError):
            Storage(name='b1', source='/nonexistent/path/xyz')

    def test_invalid_bucket_name(self):
        with pytest.raises(exceptions.StorageNameError):
            storage_lib.GcsStore('UPPER_CASE_BAD')

    def test_yaml_round_trip(self, tmp_path):
        cfg = {'name': 'bkt', 'source': str(tmp_path), 'mode': 'COPY',
               'store': 'gcs', 'persistent': False}
        s = Storage.from_yaml_config(cfg)
        assert s.mode is StorageMode.COPY
        out = s.to_yaml_config()
        assert out['name'] == 'bkt'
        assert out['mode'] == 'COPY'
        assert out['store'] == 'gcs'
        assert out['persistent'] is False

    def test_unknown_yaml_key_rejected(self):
        with pytest.raises(exceptions.InvalidTaskError):
            Storage.from_yaml_config({'name': 'b', 'frobnicate': 1})

    def test_add_store_uploads_local_source(self, tmp_path, monkeypatch):
        (tmp_path / 'f.txt').write_text('hi')
        history = []
        monkeypatch.setattr(storage_lib, '_run', _fake_run(history))
        s = Storage(name='bkt', source=str(tmp_path))
        s.add_store(StoreType.GCS)
        joined = [' '.join(c) for c in history]
        assert any('mb' in c for c in joined)         # bucket create
        assert any('rsync' in c for c in joined)      # upload

    def test_exists_skips_create(self, monkeypatch):
        calls = []

        def run(cmd, **kw):
            calls.append(cmd)
            return subprocess.CompletedProcess(cmd, 0, stdout='',
                                               stderr='')

        monkeypatch.setattr(storage_lib, '_run', run)
        store = storage_lib.GcsStore('bkt')
        store.create()
        assert not any('mb' in ' '.join(c) for c in calls)


class TestMountingUtils:

    def test_gcs_mount_cmd_idempotent(self):
        cmd = mounting_utils.get_mount_cmd('bkt', '/data')
        assert 'gcsfuse' in cmd
        assert 'mountpoint -q /data' in cmd

    def test_readonly_flag(self):
        cmd = mounting_utils.get_mount_cmd('bkt', '/data', readonly=True)
        assert '-o ro' in cmd

    def test_copy_down(self):
        cmd = mounting_utils.get_copy_down_cmd('gs://b', '/data')
        assert 'rsync' in cmd


class TestSkyignore:

    def test_skyignore_patterns(self, tmp_path):
        (tmp_path / '.skyignore').write_text('*.log\nbuild\n')
        (tmp_path / 'a.log').write_text('')
        (tmp_path / 'keep.py').write_text('')
        (tmp_path / 'build').mkdir()
        excluded = storage_utils.get_excluded_files(str(tmp_path))
        assert 'a.log' in excluded
        assert 'build' in excluded
        assert 'keep.py' not in excluded


class _FakeRunner:

    def __init__(self, node_id):
        self.node_id = node_id
        self.commands = []

    def run(self, cmd, **kwargs):
        self.commands.append(cmd)
        return 0, '', ''


class _FakeHandle:

    def __init__(self, n=2):
        self.runners = [_FakeRunner(f'host-{i}') for i in range(n)]

    def get_command_runners(self):
        return self.runners


class TestStorageMounting:

    def test_mounts_on_all_hosts(self, tmp_path):
        handle = _FakeHandle(3)
        storage = Storage(source='gs://data-bkt')
        storage_mounting.execute_storage_mounts(handle, {'/data': storage})
        for runner in handle.runners:
            assert len(runner.commands) == 1
            assert 'gcsfuse' in runner.commands[0]

    def test_copy_mode_uses_rsync(self):
        handle = _FakeHandle(1)
        storage = Storage(source='gs://data-bkt')
        storage.mode = StorageMode.COPY
        storage_mounting.execute_storage_mounts(handle, {'/data': storage})
        assert 'rsync' in handle.runners[0].commands[0]

    def test_failure_raises(self):
        handle = _FakeHandle(1)
        handle.runners[0].run = lambda cmd, **kw: (1, '', 'boom')
        storage = Storage(source='gs://data-bkt')
        with pytest.raises(exceptions.CommandError):
            storage_mounting.execute_storage_mounts(handle,
                                                    {'/data': storage})
