"""Fleet telemetry plane tests (ISSUE 11 tentpole).

The controller-side time-series store (ring buffers, windowed rates,
histogram-delta quantiles), the fleet aggregator's scrape + derived
signals (smoothed autoscaler inputs, MFU), multi-window multi-burn-rate
SLO tracking with journaled breach transitions, the spec's `slos:`
block, and the `/controller/telemetry` endpoint `sky serve top` reads.
"""
from __future__ import annotations

import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.observability import aggregator as aggregator_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import slo as slo_lib
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec


@pytest.fixture(autouse=True)
def _serve_env(monkeypatch, _isolated_home):
    monkeypatch.setenv('SKYTPU_SERVE_DB',
                       str(_isolated_home / 'serve.db'))
    global_user_state.set_enabled_clouds(['local'])
    yield


def _store(**kw) -> aggregator_lib.TimeSeriesStore:
    kw.setdefault('retention', 600)
    kw.setdefault('samples', 128)
    return aggregator_lib.TimeSeriesStore(**kw)


class TestTimeSeriesStore:

    def test_ring_buffer_and_retention_bounds(self):
        store = _store(retention=10, samples=4)
        now = time.time()
        for i in range(8):
            store.add('g', {'replica_id': '1'}, now - 8 + i, i)
        [(labels, samples)] = store.series('g')
        assert labels == {'replica_id': '1'}
        assert len(samples) == 4                     # maxlen wins
        store.add('g', {'replica_id': '1'}, now + 100, 99)
        [(_, samples)] = store.series('g')
        assert [v for _, v in samples] == [99]       # retention wins
        store.prune(now + 10000)
        assert store.series('g') == []               # dry series drop

    def test_label_sets_never_collapse(self):
        store = _store()
        now = time.time()
        store.add('g', {'replica_id': '1'}, now, 1)
        store.add('g', {'replica_id': '2'}, now, 2)
        assert len(store.series('g')) == 2
        assert store.latest('g', replica_id='2') == [
            ({'replica_id': '2'}, 2.0)]

    def test_counter_rate_and_reset_tolerance(self):
        store = _store()
        now = time.time()
        for t, v in ((50, 0), (40, 10), (30, 20)):
            store.add('c', {}, now - t, v)
        rate = store.counter_rate('c', 60, now)
        assert rate == pytest.approx(1.0)            # 20 over 20s
        # Counter reset (replica restart): post-reset value counts.
        store.add('c', {}, now - 20, 5)
        rate = store.counter_rate('c', 60, now)
        assert rate == pytest.approx(25 / 30)
        assert store.counter_rate('c', 60, now, role='x') is None

    def test_windowed_histogram_quantile(self):
        store = _store()
        now = time.time()
        # Two scrapes of a cumulative histogram: the window's delta is
        # 20 <=0.1, +20 in (0.1, 0.5], nothing beyond.
        for t, mult in ((now - 50, 1), (now - 1, 3)):
            for le, cum in (('0.1', 10), ('0.5', 20), ('+Inf', 20)):
                store.add('h_bucket', {'le': le}, t, cum * mult)
        assert store.quantile('h', 0.5, 60, now) == \
            pytest.approx(0.1)
        assert store.quantile('h', 0.75, 60, now) == \
            pytest.approx(0.3)   # interpolated inside (0.1, 0.5]
        assert store.quantile('h', 0.99, 60, now, role='x') is None

    def test_binned_sparkline_series(self):
        store = _store()
        now = time.time()
        for t, v in ((55, 0), (35, 20), (15, 40)):
            store.add('c', {}, now - t, v)
        rates = store.binned('c', 60, 6, now, mode='rate')
        assert len(rates) == 6
        assert rates[-1] is None                 # nothing in last 10s
        assert any(r and r > 0 for r in rates)
        store.add('g', {}, now - 5, 3.0)
        means = store.binned('g', 60, 6, now)
        assert means[-1] == pytest.approx(3.0)
        assert means[0] is None


class TestAggregatorScrape:

    def test_scrape_ingests_with_target_labels_and_mfu(self):
        registry = metrics_lib.Registry()
        registry.gauge('skytpu_engine_decode_tokens_per_s',
                       'tok/s').set(100.0)
        registry.gauge('skytpu_engine_model_flops_per_token',
                       'flops').set(2e9)
        registry.gauge('unrelated_series', 'ignored').set(1.0)
        port, shutdown = metrics_lib.start_exposition_server(
            registry=registry)
        try:
            agg = aggregator_lib.FleetAggregator('svc', _store())
            agg.scrape_fleet([{'url': f'http://127.0.0.1:{port}',
                               'kind': 'replica', 'replica_id': 7,
                               'role': 'decode', 'num_hosts': 1}])
        finally:
            shutdown()
        [(labels, value)] = agg.store.latest(
            'skytpu_engine_decode_tokens_per_s')
        assert labels['replica_id'] == '7'
        assert labels['role'] == 'decode'
        assert value == 100.0
        # Non-skytpu series are not ingested.
        assert agg.store.series('unrelated_series') == []
        # MFU = 100 tok/s * 2e9 flops / peak (197e12 default).
        [(mfu_labels, mfu)] = agg.store.latest('skytpu_mfu_estimate')
        assert mfu_labels['replica_id'] == '7'
        assert mfu == pytest.approx(100 * 2e9 / 197e12)

    def test_scrape_interval_gating_and_dead_target(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_SERVE_SCRAPE_INTERVAL', '3600')
        agg = aggregator_lib.FleetAggregator('svc', _store(),
                                             timeout=0.3)
        # Dead target: degrades, never raises.
        assert agg.maybe_scrape([{'url': 'http://127.0.0.1:9',
                                  'kind': 'replica',
                                  'replica_id': 1, 'role': 'mixed'}])
        # Second call inside the interval is a no-op.
        assert not agg.maybe_scrape([])

    def test_role_label_follows_live_role_morph(self):
        """PR 17 regression: after a live role morph the replica's
        health payload advertises the NEW role while the controller's
        registration-time target dict still pins the old one — each
        scrape pass must re-resolve the role from `/health` so per-role
        series (QPS, loads) follow the morph instead of going stale."""
        import http.server
        import json
        import threading

        state = {'role': 'prefill'}

        class Handler(http.server.BaseHTTPRequestHandler):

            def do_GET(self):          # noqa: N802
                if self.path.startswith('/metrics'):
                    body = ('skytpu_engine_decode_tokens_per_s '
                            '50.0\n').encode()
                    ctype = 'text/plain'
                else:                  # health payload
                    body = json.dumps({'status': 'ok',
                                       'role': state['role']}).encode()
                    ctype = 'application/json'
                self.send_response(200)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        httpd = http.server.ThreadingHTTPServer(('127.0.0.1', 0),
                                                Handler)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        port = httpd.server_address[1]
        target = {'url': f'http://127.0.0.1:{port}',
                  'kind': 'replica', 'replica_id': 3,
                  'role': 'prefill', 'num_hosts': 1}
        agg = aggregator_lib.FleetAggregator('svc', _store())
        try:
            agg.scrape_fleet([target])
            assert agg.store.latest('skytpu_engine_decode_tokens_per_s',
                                    role='prefill')
            # The replica morphs: only its health payload changes.
            state['role'] = 'decode'
            agg.scrape_fleet([target])
        finally:
            httpd.shutdown()
            httpd.server_close()
        [(labels, value)] = agg.store.latest(
            'skytpu_engine_decode_tokens_per_s', role='decode')
        assert labels['replica_id'] == '3' and value == 50.0
        # The target dict is kept in step so span/top labels agree.
        assert target['role'] == 'decode'
        # Label sets never collapse: the pre-morph samples stay under
        # the prefill-labelled series (and age out via retention)
        # while all fresh samples land under decode.
        assert agg.store.latest('skytpu_engine_decode_tokens_per_s',
                                role='prefill')

    def test_role_signals_smooth_qps_and_loads(self):
        agg = aggregator_lib.FleetAggregator('svc', _store())
        now = time.time()
        for t, v in ((40, 0), (20, 40), (0, 80)):
            agg.store.add('skytpu_lb_route_total',
                          {'role': 'decode'}, now - t, v)
        for rid, busy in (('1', 2.0), ('2', 4.0)):
            agg.store.add('skytpu_engine_busy_slots',
                          {'replica_id': rid, 'role': 'decode'},
                          now - 5, busy)
            agg.store.add('skytpu_engine_slots',
                          {'replica_id': rid, 'role': 'decode'},
                          now - 5, 8.0)
            agg.store.add('skytpu_engine_queue_depth',
                          {'replica_id': rid, 'role': 'decode'},
                          now - 5, 0.0)
        signals = agg.role_signals('decode', 60, now)
        assert signals['qps'] == pytest.approx(2.0)
        assert sorted(signals['loads']) == [
            pytest.approx(0.25), pytest.approx(0.5)]
        # No data for the prefill pool -> both None (callers keep the
        # instantaneous signals).
        empty = agg.role_signals('prefill', 60, now)
        assert empty == {'qps': None, 'loads': None}


class TestWindowedAutoscalerSignals:

    def _spec(self, **kw):
        kw.setdefault('min_replicas', 1)
        kw.setdefault('max_replicas', 10)
        kw.setdefault('target_qps_per_replica', 2.0)
        kw.setdefault('upscale_delay_seconds', 0)
        kw.setdefault('downscale_delay_seconds', 0)
        return SkyServiceSpec(**kw)

    def test_windowed_qps_replaces_timestamp_count(self):
        scaler = autoscalers.RequestRateAutoscaler(self._spec())
        now = time.time()
        # No raw timestamps at all — the smoothed signal alone drives.
        scaler.collect_windowed_signals(qps=8.0)
        decision = scaler.evaluate_scaling(now)
        assert decision.target_num_replicas == 4  # ceil(8 / 2)

    def test_none_falls_back_to_instantaneous(self):
        scaler = autoscalers.RequestRateAutoscaler(self._spec())
        now = time.time()
        scaler.collect_request_information(
            [now] * int(6 * autoscalers.QPS_WINDOW_SIZE_SECONDS), now)
        scaler.collect_windowed_signals(qps=None)
        assert scaler.evaluate_scaling(now).target_num_replicas == 3
        # A later smoothed value overrides again.
        scaler.collect_windowed_signals(qps=0.0)
        assert scaler.evaluate_scaling(
            now + 1).target_num_replicas == 1

    def test_windowed_loads_feed_slot_utilization(self):
        scaler = autoscalers.RequestRateAutoscaler(self._spec(
            target_qps_per_replica=None, target_slot_utilization=0.5))
        scaler.collect_windowed_signals(loads=[1.0, 1.0])
        assert scaler.evaluate_scaling(
            time.time()).target_num_replicas == 4

    def test_carry_over_keeps_windowed_qps(self):
        old = autoscalers.RequestRateAutoscaler(self._spec())
        old.collect_windowed_signals(qps=8.0)
        old.evaluate_scaling(time.time())
        new = autoscalers.RequestRateAutoscaler(self._spec())
        new.carry_over(old)
        assert new.windowed_qps == 8.0
        assert new.target_num_replicas == 4

    def test_warm_start_behavior_preserved(self):
        scaler = autoscalers.RequestRateAutoscaler(self._spec())
        scaler.warm_start(5)
        assert scaler.target_num_replicas == 5
        # A fresh warm-started scaler has no smoothed signal yet.
        assert scaler.windowed_qps is None


class _Journal:

    def __init__(self):
        self.events = []

    def append(self, event, **fields):
        self.events.append({'event': event, **fields})


def _fill_latency(store, now, frac_bad, total=100.0,
                  series='skytpu_engine_ttft_seconds'):
    """Scrapes whose fast- AND slow-window deltas have `frac_bad` of
    observations above 0.1s (SLO threshold 100ms sits exactly at the
    bound)."""
    for t, mult in ((now - 290, 0.0), (now - 50, 0.0), (now - 1, 1.0)):
        good = total * (1.0 - frac_bad) * mult
        for le, cum in (('0.1', good), ('+Inf', total * mult)):
            store.add(f'{series}_bucket', {'le': le}, t, cum)


class TestSLOTracker:

    def test_latency_breach_journals_start_and_end(self):
        store = _store()
        journal = _Journal()
        tracker = slo_lib.SLOTracker(
            'svc', slo_lib.parse_slos({'ttft_p99_ms': 100}),
            journal=journal)
        now = time.time()
        # 20% of requests above 100ms against a 1% budget -> burn 20x
        # in both windows -> breach.
        _fill_latency(store, now, frac_bad=0.2)
        [status] = tracker.evaluate(store, now)
        assert status['breaching']
        assert status['burn_fast'] == pytest.approx(20.0)
        assert [e['event'] for e in journal.events] == \
            ['slo_burn_start']
        assert journal.events[0]['slo'] == 'ttft_p99_ms'
        # Still breaching: no duplicate start event.
        tracker.evaluate(store, now + 1)
        assert len(journal.events) == 1
        # Recovery: an all-good fast window ends the burn.
        store2 = _store()
        _fill_latency(store2, now + 10, frac_bad=0.0)
        [status] = tracker.evaluate(store2, now + 10)
        assert not status['breaching']
        assert [e['event'] for e in journal.events] == \
            ['slo_burn_start', 'slo_burn_end']
        assert journal.events[1]['duration_s'] >= 0

    def test_multi_window_rule_one_noisy_window_does_not_page(self,
                                                              monkeypatch):
        monkeypatch.setenv('SKYTPU_SLO_FAST_WINDOW_S', '30')
        monkeypatch.setenv('SKYTPU_SLO_SLOW_WINDOW_S', '300')
        store = _store()
        journal = _Journal()
        tracker = slo_lib.SLOTracker(
            'svc', slo_lib.parse_slos({'ttft_p99_ms': 100}),
            journal=journal)
        now = time.time()
        # Bad samples confined to the OLD part of the slow window: the
        # fast window is clean -> no breach despite the slow burn.
        for t, mult in ((now - 200, 0.0), (now - 100, 1.0)):
            for le, cum in (('0.1', 0.0), ('+Inf', 100.0 * mult)):
                store.add('skytpu_engine_ttft_seconds_bucket',
                          {'le': le}, t, cum)
        [status] = tracker.evaluate(store, now)
        assert status['burn_slow'] > 1.0
        assert status['burn_fast'] == 0.0
        assert not status['breaching']
        assert journal.events == []

    def test_breach_lands_in_the_real_serve_journal(self):
        """Default journal wiring: slo_burn_start/_end are appended to
        $SKYTPU_HOME/events/serve.jsonl — the same flight-recorder
        scope the drain lifecycle uses, post-mortemable after the
        controller dies (ISSUE 11 acceptance: a slow-decode breach
        produces journal events)."""
        import os as _os

        from skypilot_tpu.observability import events as events_lib
        tracker = slo_lib.SLOTracker(
            'svc-journal', slo_lib.parse_slos({'itl_p99_ms': 100}))
        now = time.time()
        slow_decode = _store()
        # Chaos-shaped input: a delayed decode pushes inter-token gaps
        # past the 100ms objective for 30% of tokens.
        _fill_latency(slow_decode, now, frac_bad=0.3,
                      series='skytpu_engine_itl_seconds')
        [status] = tracker.evaluate(slow_decode, now)
        assert status['breaching']
        recovered = _store()
        _fill_latency(recovered, now + 5, frac_bad=0.0,
                      series='skytpu_engine_itl_seconds')
        tracker.evaluate(recovered, now + 5)
        journal = events_lib.get_journal(_os.path.join(
            events_lib.journal_root(), 'serve.jsonl'))
        events = [e for e in journal.read()
                  if e.get('service') == 'svc-journal']
        assert [e['event'] for e in events] == \
            ['slo_burn_start', 'slo_burn_end']
        assert events[0]['slo'] == 'itl_p99_ms'
        assert events[1]['duration_s'] >= 0

    def test_no_traffic_is_no_burn(self):
        tracker = slo_lib.SLOTracker(
            'svc', slo_lib.parse_slos(
                {'ttft_p99_ms': 100, 'error_rate': 0.01,
                 'availability': 0.999}))
        statuses = tracker.evaluate(_store(), time.time())
        assert len(statuses) == 3
        assert all(not s['breaching'] and s['burn_fast'] == 0
                   for s in statuses)

    def test_error_rate_and_availability_burns(self):
        store = _store()
        now = time.time()
        for t, mult in ((now - 50, 0.0), (now - 1, 1.0)):
            store.add('skytpu_lb_requests_total', {}, t, 1000 * mult)
            store.add('skytpu_lb_upstream_errors_total', {}, t,
                      50 * mult)
            store.add('skytpu_lb_no_replica_total', {}, t, 10 * mult)
        tracker = slo_lib.SLOTracker(
            'svc', slo_lib.parse_slos({'error_rate': 0.01,
                                       'availability': 0.999}))
        by_name = {s['slo']: s for s in tracker.evaluate(store, now)}
        # 5% errors on a 1% budget; 1% no-replica on a 0.1% budget.
        assert by_name['error_rate']['burn_fast'] == pytest.approx(
            5.0, rel=1e-3)
        assert by_name['availability']['burn_fast'] == pytest.approx(
            10.0, rel=1e-3)
        assert by_name['error_rate']['breaching']


class TestSLOSpecBlock:

    def test_round_trip(self):
        spec = SkyServiceSpec.from_yaml_config({
            'replicas': 1,
            'slos': {'ttft_p99_ms': 500, 'itl_p99_ms': 100,
                     'error_rate': 0.01, 'availability': 0.999}})
        assert spec.slos == {'ttft_p99_ms': 500.0,
                             'itl_p99_ms': 100.0,
                             'error_rate': 0.01,
                             'availability': 0.999}
        again = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
        assert again.slos == spec.slos
        assert SkyServiceSpec.from_yaml_config(
            {'replicas': 1}).slos is None

    @pytest.mark.parametrize('bad', [
        {'bogus_key': 1},
        {'ttft_p99_ms': -5},
        {'error_rate': 1.5},
        {'availability': 0.0},
        {'ttft_p99_ms': 'fast'},
    ])
    def test_validation(self, bad):
        with pytest.raises(exceptions.InvalidTaskError):
            SkyServiceSpec(slos=bad)

    def test_parse_slos_objects(self):
        slos = slo_lib.parse_slos({'ttft_p99_ms': 500,
                                   'availability': 0.99})
        by_name = {s.name: s for s in slos}
        assert by_name['ttft_p99_ms'].threshold_s == \
            pytest.approx(0.5)
        assert by_name['ttft_p99_ms'].budget == pytest.approx(0.01)
        assert by_name['availability'].budget == pytest.approx(0.01)
        assert slo_lib.parse_slos(None) == []


class TestControllerTelemetryEndpoint:

    def test_telemetry_payload_shape(self):
        import requests

        from skypilot_tpu.serve.controller import SkyServeController
        from skypilot_tpu.utils import common_utils
        import os as _os
        task = sky.Task(name='svc-tel', run='echo hi')
        task.set_resources(sky.Resources(cloud='local'))
        task.service = SkyServiceSpec(
            min_replicas=1, max_replicas=1,
            slos={'ttft_p99_ms': 500})
        yaml_dir = common_utils.ensure_dir(
            _os.path.join(common_utils.skytpu_home(), 'serve'))
        yaml_path = _os.path.join(yaml_dir, 'svc-tel.yaml')
        common_utils.dump_yaml(yaml_path, task.to_yaml_config())
        serve_state.add_service('svc-tel',
                                task.service.to_yaml_config(),
                                yaml_path)
        controller = SkyServeController('svc-tel')
        port = controller.start_http()
        try:
            # Seed some history so the snapshot carries numbers.
            now = time.time()
            controller.aggregator.store.add(
                'skytpu_lb_route_total', {'role': 'mixed'},
                now - 30, 0)
            controller.aggregator.store.add(
                'skytpu_lb_route_total', {'role': 'mixed'}, now, 60)
            controller.slo_tracker.evaluate(
                controller.aggregator.store, now)
            resp = requests.get(
                f'http://127.0.0.1:{port}/controller/telemetry',
                timeout=5)
            assert resp.status_code == 200
            payload = resp.json()
            assert payload['service'] == 'svc-tel'
            assert 'mixed' in payload['roles']
            assert payload['roles']['mixed']['qps'] == \
                pytest.approx(2.0)
            assert len(payload['roles']['mixed']['qps_spark']) > 0
            assert payload['slos'][0]['slo'] == 'ttft_p99_ms'
            assert payload['slow_traces'] == []
        finally:
            controller.stop()


class TestServeTopRender:

    def _record(self):
        return {'name': 'svc', 'status': 'READY', 'version': 1,
                'load_balancer_port': 8080,
                'replicas': [
                    {'replica_id': 1, 'role': 'decode',
                     'status': 'READY', 'url': 'http://r1'},
                    {'replica_id': 2, 'role': 'prefill',
                     'status': 'READY', 'url': 'http://r2'},
                ]}

    def test_render_shows_fleet_slos_and_breach(self, capsys):
        from skypilot_tpu import cli
        telemetry = {
            'mfu': {'1': 0.1234},
            'roles': {'decode': {
                'qps': 3.5, 'qps_spark': [1.0, 2.0, None, 4.0],
                'tokens_per_s_spark': [10.0, 20.0],
                'ttft_p99_ms': 120.0, 'itl_p99_ms': 9.0}},
            'slos': [{'slo': 'ttft_p99_ms', 'target': 100,
                      'burn_fast': 20.0, 'burn_slow': 15.0,
                      'breaching': True}],
            'slow_traces': [{'request_id': 'abcd', 'replica_id': 1,
                             'role': 'decode', 'duration_ms': 812.0,
                             'ttft_ms': 300.0, 'status': 'ok'}],
        }
        cli._render_top([self._record()], {'svc': telemetry})  # pylint: disable=protected-access
        out = capsys.readouterr().out
        assert 'svc' in out and '2/2 ready' in out
        assert '0.1234' in out                  # per-replica MFU
        assert 'BREACH' in out                  # SLO status
        assert 'abcd' in out and '812.0ms' in out
        assert 'TTFT p99' in out

    def test_render_tick_breakdown_and_recompiles_columns(
            self, capsys):
        from skypilot_tpu import cli
        telemetry = {
            'mfu': {'1': 0.1234},
            'roles': {},
            'slos': [],
            'slow_traces': [],
            'tick_breakdown': {'1': {'decode-step': 0.6,
                                     'prefill-chunk': 0.3,
                                     'admit': 0.1}},
            'recompiles': {'1': 2.0},
        }
        cli._render_top([self._record()], {'svc': telemetry})  # pylint: disable=protected-access
        out = capsys.readouterr().out
        assert 'TICK-BREAKDOWN' in out and 'RECOMPILES' in out
        # Top-2 phases by share, largest first.
        assert 'decode-step 60%' in out
        assert 'prefill-chunk 30%' in out
        assert 'admit' not in out.split('TICK-BREAKDOWN')[1]
        assert ' 2 ' in out or ' 2\n' in out  # recompile count rendered

    def test_fmt_tick_breakdown(self):
        from skypilot_tpu import cli
        assert cli._fmt_tick_breakdown(None) == '-'  # pylint: disable=protected-access
        assert cli._fmt_tick_breakdown({}) == '-'  # pylint: disable=protected-access
        got = cli._fmt_tick_breakdown(  # pylint: disable=protected-access
            {'sample': 0.25, 'decode-step': 0.75})
        assert got == 'decode-step 75% sample 25%'

    def test_fleet_snapshot_carries_profiling_series(self):
        agg = aggregator_lib.FleetAggregator('svc', _store())
        now = time.time()
        for t, v in ((40, 1.0), (20, 7.0), (0, 13.0)):
            agg.store.add('skytpu_engine_tick_phase_seconds_sum',
                          {'replica_id': '1', 'phase': 'decode-step'},
                          now - t, v)
        agg.store.add('skytpu_engine_recompiles_total',
                      {'replica_id': '1', 'fn': 'step'}, now, 2.0)
        agg.store.add('skytpu_engine_recompiles_total',
                      {'replica_id': '1', 'fn': 'prefill'}, now, 1.0)
        snap = agg.fleet_snapshot(['mixed'], now=now)
        # 12s of decode-step time over 40s of wall = 0.3 s/s.
        assert snap['tick_breakdown']['1']['decode-step'] == \
            pytest.approx(0.3)
        # Recompiles sum across jit entries per replica.
        assert snap['recompiles']['1'] == pytest.approx(3.0)

    def test_render_without_telemetry_still_shows_fleet(self, capsys):
        from skypilot_tpu import cli
        cli._render_top([self._record()], {'svc': None})  # pylint: disable=protected-access
        out = capsys.readouterr().out
        assert 'REPLICA' in out and 'BREACH' not in out

    def test_sparkline(self):
        from skypilot_tpu import cli
        spark = cli._sparkline([0.0, 1.0, 2.0, None, 4.0])  # pylint: disable=protected-access
        assert len(spark) == 5
        assert spark[3] == ' '
        assert spark[4] == '█'
        assert cli._sparkline([]) == '-'  # pylint: disable=protected-access
        assert cli._sparkline([None, None]) == '-'  # pylint: disable=protected-access
