"""Spawn-time daemon registry: crash-safe orphan reaping (VERDICT r2
weak #5 — session fixtures never run on kill -9; the registry must)."""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import psutil
import pytest

from skypilot_tpu.utils import daemon_registry


@pytest.fixture
def _registry(tmp_path, monkeypatch):
    path = str(tmp_path / 'registry.jsonl')
    monkeypatch.setenv('SKYTPU_DAEMON_REGISTRY', path)
    yield path


def _spawn_sleeper():
    return subprocess.Popen([sys.executable, '-c',
                             'import time; time.sleep(600)'],
                            stdin=subprocess.DEVNULL,
                            stdout=subprocess.DEVNULL,
                            start_new_session=True)


def test_register_appends_record(_registry, tmp_path):
    proc = _spawn_sleeper()
    try:
        daemon_registry.register(proc.pid, 'skylet',
                                 home=str(tmp_path))
        recs = daemon_registry._load()
        assert len(recs) == 1
        assert recs[0]['pid'] == proc.pid
        assert recs[0]['kind'] == 'skylet'
        assert recs[0]['create_time'] is not None
    finally:
        proc.kill()


def test_reap_kills_daemon_with_vanished_home(_registry, tmp_path):
    """The kill -9 scenario: a daemon whose (tmp) home was deleted is an
    orphan and must be reaped by the NEXT run's startup."""
    home = tmp_path / 'fake_home'
    home.mkdir()
    proc = _spawn_sleeper()
    try:
        daemon_registry.register(proc.pid, 'skylet', home=str(home))
        # Home still exists: not reaped.
        assert daemon_registry.reap_stale() == 0
        assert psutil.pid_exists(proc.pid)
        # Simulate the deleted test home.
        home.rmdir()
        assert daemon_registry.reap_stale() == 1
        # Kill delivered; the process is gone (or a zombie child of us).
        time.sleep(0.2)
        assert (not psutil.pid_exists(proc.pid) or
                psutil.Process(proc.pid).status() ==
                psutil.STATUS_ZOMBIE)
    finally:
        try:
            proc.kill()
        except Exception:  # pylint: disable=broad-except
            pass
        proc.wait(timeout=5)


def test_reap_prunes_dead_entries(_registry, tmp_path):
    proc = _spawn_sleeper()
    daemon_registry.register(proc.pid, 'skylet', home=str(tmp_path))
    proc.kill()
    proc.wait(timeout=5)
    daemon_registry.reap_stale()
    assert daemon_registry._load() == []


def test_pid_reuse_guard(_registry, tmp_path):
    """A recorded pid now naming a DIFFERENT process must not be
    killed."""
    proc = _spawn_sleeper()
    try:
        # Record the live pid but with a create_time from long ago —
        # as if the original daemon died and the pid was reused.
        rec = {'pid': proc.pid, 'kind': 'skylet',
               'home': str(tmp_path / 'gone'),
               'create_time': time.time() - 10_000,
               'registered_at': time.time() - 10_000}
        with open(_registry, 'w', encoding='utf-8') as f:
            f.write(json.dumps(rec) + '\n')
        assert daemon_registry.reap_stale() == 0
        assert psutil.pid_exists(proc.pid)
    finally:
        proc.kill()
        proc.wait(timeout=5)


def test_corrupt_lines_ignored(_registry):
    with open(_registry, 'w', encoding='utf-8') as f:
        f.write('not json\n{"pid": 999999999, "kind": "x", '
                '"home": "/nonexistent", "create_time": 1.0, '
                '"registered_at": 1.0}\n')
    assert daemon_registry.reap_stale() == 0
    assert daemon_registry._load() == []
