"""Upgrade-path / backward-compat tests (VERDICT r3 item 8 / r4 #6).

Model: /root/reference/tests/backward_compatibility_tests.sh — launch a
cluster from one client version, upgrade the client, and verify each
verb class against the old remote runtime.  The reference does this
with real wheels on real clouds; here the runtime version the cluster
launched with is recorded in its handle (the app tree is shipped at
provision), so a client upgrade is simulated by bumping
`skypilot_tpu.__version__` after launch — the remote runtime genuinely
remains the old one until a relaunch re-ships it.

Policy under test (backend_utils.check_remote_runtime_version):
- read-only verbs (status/queue/logs) always work;
- minor/patch skew: exec proceeds with a warning;
- MAJOR skew: exec refuses (RuntimeVersionSkewError);
- relaunch re-ships the runtime and clears the skew.
"""
from __future__ import annotations

import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import status_lib
from skypilot_tpu.backends import backend_utils


def _wait_job(cluster: str, job_id: int, timeout: float = 60.0) -> str:
    deadline = time.time() + timeout
    while time.time() < deadline:
        statuses = sky.job_status(cluster, [job_id])
        value = statuses.get(str(job_id))
        if value in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP',
                     'FAILED_DRIVER', 'CANCELLED'):
            return value
        time.sleep(0.5)
    raise TimeoutError(f'Job {job_id} did not finish; last={statuses}')


@pytest.fixture
def local_infra():
    global_user_state.set_enabled_clouds(['local'])
    yield
    for record in global_user_state.get_clusters():
        try:
            sky.down(record['name'])
        except Exception:  # pylint: disable=broad-except
            pass


def _task(name='t'):
    task = sky.Task(name=name, run=f'echo ran-{name}')
    task.set_resources(sky.Resources(cloud='local'))
    return task


def _upgrade_client(monkeypatch, version: str) -> None:
    """The 'pip install -U' moment: only the CLIENT changes; the
    cluster's recorded runtime version stays what launch shipped."""
    import skypilot_tpu
    monkeypatch.setattr(skypilot_tpu, '__version__', version)


def test_minor_upgrade_warns_but_works(local_infra, monkeypatch, caplog):
    job1 = sky.launch(_task('a'), cluster_name='up1', stream_logs=False,
                      detach_run=True)
    assert _wait_job('up1', job1) == 'SUCCEEDED'
    import skypilot_tpu
    old = skypilot_tpu.__version__
    major = old.split('.', 1)[0]
    _upgrade_client(monkeypatch, f'{major}.999.0')

    # Read-only verbs against the old runtime.
    assert backend_utils.refresh_cluster_status(
        'up1') == status_lib.ClusterStatus.UP
    queue = sky.queue('up1')
    assert any(row['job_id'] == job1 for row in queue)

    # Exec proceeds, with the skew warning naming both versions
    # (sky_logging detaches from the root logger, so capture via
    # propagation on the execution module's logger).
    import logging
    monkeypatch.setattr(
        logging.getLogger('skypilot_tpu'), 'propagate', True)
    with caplog.at_level('WARNING'):
        job2 = sky.exec(_task('b'), cluster_name='up1',
                        stream_logs=False, detach_run=True)
    assert _wait_job('up1', job2) == 'SUCCEEDED'
    skew_logs = [r.message for r in caplog.records
                 if 'runs skypilot_tpu' in r.message]
    assert skew_logs and old in skew_logs[0]
    assert f'{major}.999.0' in skew_logs[0]


def test_major_upgrade_blocks_exec_not_reads(local_infra, monkeypatch):
    job1 = sky.launch(_task('a'), cluster_name='up2', stream_logs=False,
                      detach_run=True)
    assert _wait_job('up2', job1) == 'SUCCEEDED'
    import skypilot_tpu
    old_major = int(skypilot_tpu.__version__.split('.', 1)[0])
    _upgrade_client(monkeypatch, f'{old_major + 1}.0.0')

    # Old cluster stays inspectable from the new client.
    assert backend_utils.refresh_cluster_status(
        'up2') == status_lib.ClusterStatus.UP
    assert sky.queue('up2')
    assert sky.job_status('up2', [job1])[str(job1)] == 'SUCCEEDED'

    # But exec refuses: the wire contract may have changed.
    with pytest.raises(exceptions.RuntimeVersionSkewError,
                       match='major version apart'):
        sky.exec(_task('b'), cluster_name='up2', stream_logs=False,
                 detach_run=True)

    # Relaunch re-ships the runtime under the NEW version; exec works.
    job3 = sky.launch(_task('c'), cluster_name='up2', stream_logs=False,
                      detach_run=True)
    assert _wait_job('up2', job3) == 'SUCCEEDED'
    handle = global_user_state.get_cluster_from_name('up2')['handle']
    assert handle.launched_runtime_version == f'{old_major + 1}.0.0'
    job4 = sky.exec(_task('d'), cluster_name='up2', stream_logs=False,
                    detach_run=True)
    assert _wait_job('up2', job4) == 'SUCCEEDED'


def test_stop_start_heals_major_skew(local_infra, monkeypatch):
    """The skew error's other documented healing path: stop/start
    re-ships the runtime from the new client and restamps the handle,
    so exec works again."""
    job1 = sky.launch(_task('a'), cluster_name='up4', stream_logs=False,
                      detach_run=True)
    assert _wait_job('up4', job1) == 'SUCCEEDED'
    import skypilot_tpu
    old_major = int(skypilot_tpu.__version__.split('.', 1)[0])
    _upgrade_client(monkeypatch, f'{old_major + 1}.0.0')
    with pytest.raises(exceptions.RuntimeVersionSkewError):
        sky.exec(_task('b'), cluster_name='up4', stream_logs=False,
                 detach_run=True)
    sky.stop('up4')
    sky.start('up4')
    handle = global_user_state.get_cluster_from_name('up4')['handle']
    assert handle.launched_runtime_version == f'{old_major + 1}.0.0'
    job2 = sky.exec(_task('c'), cluster_name='up4', stream_logs=False,
                    detach_run=True)
    assert _wait_job('up4', job2) == 'SUCCEEDED'


def test_dryrun_relaunch_has_no_side_effects(local_infra, monkeypatch):
    """Dryrun on an existing skewed cluster must not re-ship or
    restamp anything."""
    sky.launch(_task('a'), cluster_name='up5', stream_logs=False,
               detach_run=True)
    import skypilot_tpu
    old = skypilot_tpu.__version__
    _upgrade_client(monkeypatch, '0.999.0')
    sky.launch(_task('b'), cluster_name='up5', stream_logs=False,
               detach_run=True, dryrun=True)
    handle = global_user_state.get_cluster_from_name('up5')['handle']
    assert handle.launched_runtime_version == old  # untouched


def test_prestamp_handle_is_tolerated(local_infra, monkeypatch):
    """Handles from clients older than the version stamp (no
    launched_runtime_version attribute after unpickling) must not
    break the check — unknowable means silent."""
    sky.launch(_task('a'), cluster_name='up3', stream_logs=False,
               detach_run=True)
    handle = global_user_state.get_cluster_from_name('up3')['handle']
    monkeypatch.delattr(type(handle), 'launched_runtime_version',
                        raising=False)
    if hasattr(handle, 'launched_runtime_version'):
        del handle.launched_runtime_version
    assert backend_utils.check_remote_runtime_version(handle) is None


def test_skew_policy_unit():
    """The policy table, straight against the check function."""
    class FakeHandle:
        cluster_name = 'c'

        def __init__(self, version):
            self.launched_runtime_version = version

    import skypilot_tpu
    local = skypilot_tpu.__version__
    assert backend_utils.check_remote_runtime_version(
        FakeHandle(local)) is None
    major = local.split('.', 1)[0]
    warn = backend_utils.check_remote_runtime_version(
        FakeHandle(f'{major}.0.0.dev0'))
    assert warn is not None and 'resync' in warn
    with pytest.raises(exceptions.RuntimeVersionSkewError):
        backend_utils.check_remote_runtime_version(
            FakeHandle(f'{int(major) + 1}.0.0'))
    # Non-numeric versions (dev builds) degrade to a warning, never a
    # hard block.
    assert backend_utils.check_remote_runtime_version(
        FakeHandle('dev')) is not None
