"""AWS catalog fetcher (public bulk pricing feed, injectable
transport — no boto3, no network in tests)."""
from __future__ import annotations

import json
import os

import pytest

from skypilot_tpu import catalog
from skypilot_tpu.catalog.data_fetchers import fetch_aws


def _product(sku, itype, vcpu, mem, gpu=0, os_name='Linux',
             tenancy='Shared', presw='NA', capacity='Used'):
    return sku, {
        'attributes': {
            'instanceType': itype, 'vcpu': str(vcpu),
            'memory': f'{mem} GiB', 'gpu': str(gpu),
            'operatingSystem': os_name, 'tenancy': tenancy,
            'preInstalledSw': presw, 'capacitystatus': capacity,
        }
    }


def _term(sku, price):
    return sku, {
        f'{sku}.offer': {
            'priceDimensions': {
                f'{sku}.dim': {'pricePerUnit': {'USD': str(price)}}
            }
        }
    }


def _payload():
    products = dict([
        _product('SKU1', 'p4d.24xlarge', 96, 1152, gpu=8),
        _product('SKU2', 'm6i.2xlarge', 8, 32),
        # Filtered out: wrong OS, dedicated tenancy, SQL preinstalled,
        # reserved capacity, uninteresting family.
        _product('SKU3', 'p4d.24xlarge', 96, 1152, gpu=8,
                 os_name='Windows'),
        _product('SKU4', 'p4d.24xlarge', 96, 1152, gpu=8,
                 tenancy='Dedicated'),
        _product('SKU5', 'g5.xlarge', 4, 16, gpu=1, presw='SQL Std'),
        _product('SKU6', 'p3.2xlarge', 8, 61, gpu=1,
                 capacity='AllocatedCapacityReservation'),
        _product('SKU7', 'c7g.xlarge', 4, 8),
    ])
    terms = {'OnDemand': dict([
        _term('SKU1', 32.77), _term('SKU2', 0.384), _term('SKU3', 50.0),
        _term('SKU4', 40.0), _term('SKU5', 1.5), _term('SKU6', 3.06),
        _term('SKU7', 0.145),
    ])}
    return {'products': products, 'terms': terms}


class TestParse:

    def test_filters_and_maps(self):
        rows = fetch_aws.parse_region(_payload(), 'us-east-1')
        by_type = {r['InstanceType'] for r in rows}
        assert by_type == {'p4d.24xlarge', 'm6i.2xlarge'}
        p4d = [r for r in rows if r['InstanceType'] == 'p4d.24xlarge']
        assert len(p4d) == 3  # one per zone suffix
        assert p4d[0]['AcceleratorName'] == 'A100'
        assert p4d[0]['AcceleratorCount'] == 8
        assert p4d[0]['Price'] == pytest.approx(32.77)
        assert p4d[0]['SpotPrice'] == ''  # never synthesized

    def test_no_price_skipped(self):
        payload = _payload()
        del payload['terms']['OnDemand']['SKU1']
        rows = fetch_aws.parse_region(payload, 'us-east-1')
        assert all(r['InstanceType'] != 'p4d.24xlarge' for r in rows)


class TestFetch:

    def test_fetch_writes_csv_and_feeds_queries(self, tmp_path):
        calls = []

        def transport(url):
            calls.append(url)
            return _payload()

        out = fetch_aws.fetch(transport, regions=['us-east-1'],
                              output_dir=str(tmp_path))
        assert os.path.exists(out['aws_instances.csv'])
        meta = json.load(open(out['aws_instances.csv'] + '.meta.json',
                              encoding='utf-8'))
        assert meta['num_rows'] == 6
        assert 'us-east-1' in calls[0]

    def test_refresh_via_catalog_api(self, _isolated_home):
        catalog.refresh('aws', transport=lambda url: _payload(),
                        regions=['us-east-1'])
        cost = catalog.get_hourly_cost('aws', 'p4d.24xlarge')
        assert cost == pytest.approx(32.77)
        ages = catalog.catalog_age_hours('aws')
        assert ages['aws_instances.csv'] is not None

    def test_empty_parse_refuses(self, tmp_path):
        with pytest.raises(RuntimeError, match='refusing'):
            fetch_aws.fetch(lambda url: {'products': {}, 'terms': {}},
                            regions=['us-east-1'],
                            output_dir=str(tmp_path))
