"""LogRecordRing under concurrent export + eviction (ISSUE 19
satellite), mirroring test_span_store_concurrency.py.

Parallel exporters paginating with the exact `since=` seq cursor while
a writer races the ring bound: an exporter must never see a record
twice, never miss a record that survived long enough to be seen, and
the ring must never exceed its cap.
"""
from __future__ import annotations

import threading

from skypilot_tpu.observability import logs as logs_lib


def _rec(i: int) -> dict:
    return {'ts': 1000.0 + i * 1e-3, 'level': 'INFO', 'levelno': 20,
            'logger': 'ring_test', 'msg': f'line {i:05d}',
            'request_id': f'r{i % 7}'}


class _Exporter(threading.Thread):
    """Pages `export(since=cursor)` in a loop, deduping nothing —
    duplicates are a failure, not something to paper over."""

    def __init__(self, ring, done: threading.Event) -> None:
        super().__init__(daemon=True)
        self.ring = ring
        self.done = done
        self.seen = []
        self.duplicates = []

    def run(self) -> None:
        cursor = None
        seen_msgs = set()
        while True:
            finished = self.done.is_set()
            page = self.ring.export(since=cursor)
            for rec in page:
                if rec['msg'] in seen_msgs:
                    self.duplicates.append(rec['msg'])
                seen_msgs.add(rec['msg'])
                self.seen.append(rec)
            if page:
                # seq is unique + monotonic and `since=` is strictly
                # after: the cursor IS the last seq, no epsilon fudge.
                cursor = page[-1]['seq']
            if finished:
                return


class TestLogRingConcurrency:

    CAP = 64
    WRITES = 600

    def test_parallel_export_races_eviction(self):
        ring = logs_lib.LogRecordRing(maxlen=self.CAP)
        done = threading.Event()
        exporters = [_Exporter(ring, done) for _ in range(4)]
        for exp in exporters:
            exp.start()

        cap_violations = []
        for i in range(self.WRITES):
            ring.add(_rec(i))
            if len(ring) > self.CAP:
                cap_violations.append(len(ring))
        done.set()
        for exp in exporters:
            exp.join(timeout=30)
            assert not exp.is_alive()

        assert not cap_violations
        final = ring.export()
        final_msgs = [r['msg'] for r in final]
        assert len(final_msgs) == self.CAP         # exactly the cap
        # Stamped seqs are unique + monotonic across the whole run.
        final_seqs = [r['seq'] for r in final]
        assert final_seqs == sorted(final_seqs)
        assert len(set(final_seqs)) == len(final_seqs)
        for exp in exporters:
            # Never a duplicate, pages in order.
            assert exp.duplicates == []
            seqs = [r['seq'] for r in exp.seen]
            assert seqs == sorted(seqs)
            # Never a dropped unseen record: everything still in the
            # ring at the end was either exported earlier or picked up
            # by the exporter's final page — the union must cover the
            # survivors completely.
            seen_msgs = {r['msg'] for r in exp.seen}
            assert seen_msgs >= set(final_msgs)

    def test_filters_stay_consistent_under_writes(self):
        ring = logs_lib.LogRecordRing(maxlen=32)
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                try:
                    page = ring.export(limit=8)
                    assert len(page) <= 8
                    one = ring.export(request_id='r3')
                    assert all(r['request_id'] == 'r3' for r in one)
                    grepped = ring.export(grep=r'line 0\d+')
                    assert all('line 0' in r['msg'] for r in grepped)
                except Exception as e:  # pylint: disable=broad-except
                    errors.append(e)
                    return

        threads = [threading.Thread(target=reader, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        for i in range(400):
            ring.add(_rec(i))
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert errors == []

    def test_export_copies_are_isolated(self):
        """Exported dicts are copies: a consumer mutating its page must
        not corrupt the ring other exporters read."""
        ring = logs_lib.LogRecordRing(maxlen=8)
        ring.add(_rec(0))
        page = ring.export()
        page[0]['msg'] = 'clobbered'
        assert ring.export()[0]['msg'] == 'line 00000'
