"""Unit tests: L0 foundation (state store, config, utils, logging)."""
from __future__ import annotations

import os
import time

import pytest

from skypilot_tpu import config as config_mod
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import status_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils


class FakeHandle:
    def __init__(self, name):
        self.cluster_name = name
        self.launched_resources = {'accelerator': 'tpu-v5e-8'}
        self.launched_nodes = 1


class TestGlobalUserState:

    def test_add_and_get_cluster(self):
        handle = FakeHandle('c1')
        global_user_state.add_or_update_cluster('c1', handle, {'r'}, ready=False)
        rec = global_user_state.get_cluster_from_name('c1')
        assert rec is not None
        assert rec['status'] == status_lib.ClusterStatus.INIT
        assert rec['handle'].cluster_name == 'c1'
        assert not rec['cluster_ever_up']

        global_user_state.add_or_update_cluster('c1', handle, {'r'}, ready=True)
        rec = global_user_state.get_cluster_from_name('c1')
        assert rec['status'] == status_lib.ClusterStatus.UP
        assert rec['cluster_ever_up']

    def test_status_transitions_and_remove(self):
        handle = FakeHandle('c2')
        global_user_state.add_or_update_cluster('c2', handle, set(), ready=True)
        global_user_state.set_cluster_status(
            'c2', status_lib.ClusterStatus.STOPPED)
        rec = global_user_state.get_cluster_from_name('c2')
        assert rec['status'] == status_lib.ClusterStatus.STOPPED
        global_user_state.remove_cluster('c2', terminate=True)
        assert global_user_state.get_cluster_from_name('c2') is None

    def test_set_status_missing_cluster_raises(self):
        with pytest.raises(ValueError):
            global_user_state.set_cluster_status(
                'nope', status_lib.ClusterStatus.UP)

    def test_autostop(self):
        global_user_state.add_or_update_cluster('c3', FakeHandle('c3'), set(),
                                                ready=True)
        global_user_state.set_cluster_autostop_value('c3', 10, to_down=True)
        rec = global_user_state.get_cluster_from_name('c3')
        assert rec['autostop'] == 10
        assert rec['to_down'] is True

    def test_glob(self):
        for name in ('train-1', 'train-2', 'serve-1'):
            global_user_state.add_or_update_cluster(name, FakeHandle(name),
                                                    set(), ready=True)
        assert sorted(global_user_state.get_glob_cluster_names('train-*')) == [
            'train-1', 'train-2'
        ]

    def test_cost_report_duration(self):
        global_user_state.add_or_update_cluster('c4', FakeHandle('c4'), {'r'},
                                                ready=True)
        time.sleep(1.1)
        global_user_state.set_cluster_status(
            'c4', status_lib.ClusterStatus.STOPPED)
        history = global_user_state.get_clusters_from_history()
        rec = [h for h in history if h['name'] == 'c4'][0]
        assert rec['duration'] >= 1

    def test_enabled_clouds_roundtrip(self):
        global_user_state.set_enabled_clouds(['gcp', 'local'])
        assert set(global_user_state.get_enabled_clouds()) == {'gcp', 'local'}


class TestConfig:

    def test_missing_config_defaults(self):
        assert config_mod.get_nested(('tpu', 'runtime_version'), 'x') == 'x'

    def test_load_and_get_nested(self, _isolated_home):
        cfg = _isolated_home / 'config.yaml'
        cfg.write_text('tpu:\n  runtime_version: v2-alpha-tpuv5-lite\n')
        config_mod.reload_config()
        assert config_mod.get_nested(
            ('tpu', 'runtime_version'), None) == 'v2-alpha-tpuv5-lite'

    def test_invalid_config_rejected(self, _isolated_home):
        cfg = _isolated_home / 'config.yaml'
        cfg.write_text('bogus_key: 1\n')
        config_mod.reload_config()
        with pytest.raises(exceptions.InvalidSkyTpuConfigError):
            config_mod.get_nested(('tpu',), None)

    def test_task_override_allowed_keys_only(self, _isolated_home):
        cfg = _isolated_home / 'config.yaml'
        cfg.write_text('tpu:\n  runtime_version: a\n')
        config_mod.reload_config()
        v = config_mod.get_nested(('tpu', 'runtime_version'), None,
                                  override_configs={'tpu': {'runtime_version': 'b'}})
        assert v == 'b'
        with pytest.raises(exceptions.InvalidSkyTpuConfigError):
            config_mod.get_nested(('gcp', 'project_id'), None,
                                  override_configs={'gcp': {'project_id': 'x'}})


class TestCommonUtils:

    def test_user_hash_stable(self):
        h1 = common_utils.get_user_hash()
        h2 = common_utils.get_user_hash()
        assert h1 == h2
        assert len(h1) == common_utils.USER_HASH_LENGTH

    def test_cluster_name_validation(self):
        common_utils.check_cluster_name_is_valid('ok-name_1')
        with pytest.raises(exceptions.InvalidClusterNameError):
            common_utils.check_cluster_name_is_valid('1bad')
        with pytest.raises(exceptions.InvalidClusterNameError):
            common_utils.check_cluster_name_is_valid('a' * 80)
        common_utils.check_cluster_name_is_valid(None)

    def test_cluster_name_on_cloud_truncates(self):
        name = common_utils.make_cluster_name_on_cloud('x' * 60, max_length=30)
        assert len(name) <= 30
        assert common_utils.get_user_hash() in name

    def test_backoff_grows(self):
        b = common_utils.Backoff(initial_backoff=1.0)
        v1 = b.current_backoff
        v2 = b.current_backoff
        # Jitter is +/-40%, so two samples can overlap — assert each
        # sample's jitter envelope and the deterministic base growth.
        assert 0.6 <= v1 <= 1.4
        assert 0.96 <= v2 <= 2.24
        assert b._backoff == pytest.approx(1.6)  # pylint: disable=protected-access

    def test_yaml_roundtrip(self, tmp_path):
        path = str(tmp_path / 'x.yaml')
        common_utils.dump_yaml(path, {'a': 1, 'b': None})
        assert common_utils.read_yaml(path) == {'a': 1, 'b': None}


class TestSubprocessUtils:

    def test_run_in_parallel_order(self):
        out = subprocess_utils.run_in_parallel(lambda x: x * 2, [3, 1, 2])
        assert out == [6, 2, 4]

    def test_run_in_parallel_raises(self):
        def boom(x):
            raise RuntimeError('x')
        with pytest.raises(RuntimeError):
            subprocess_utils.run_in_parallel(boom, [1, 2])

    def test_handle_returncode(self):
        subprocess_utils.handle_returncode(0, 'true', 'no')
        with pytest.raises(exceptions.CommandError):
            subprocess_utils.handle_returncode(1, 'false', 'failed',
                                               stream_logs=False)

    def test_run_with_retries_retry_on_stderr(self):
        rc, _, _ = subprocess_utils.run_with_retries('true')
        assert rc == 0
        rc, _, _ = subprocess_utils.run_with_retries(
            'echo flaky >&2; false', max_retry=1, retry_stderrs=['flaky'])
        assert rc != 0
