"""Generic Kubernetes provisioner + cloud tests against a faked kubectl.

Mirrors the reference's k8s coverage goals
(/root/reference/sky/provision/kubernetes/) hermetically: the kubectl
CLI sits behind the injectable `set_cli_runner` seam.
"""
from __future__ import annotations

import json
import subprocess
from typing import Dict, List

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common as pcommon
from skypilot_tpu.provision.kubernetes import instance as k8s
from skypilot_tpu.status_lib import ClusterStatus


class FakeKubectl:
    """Emulates pods + services in memory."""

    def __init__(self):
        self.pods: Dict[str, dict] = {}
        self.services: Dict[str, dict] = {}
        self.commands: List[List[str]] = []

    def __call__(self, argv, stdin=None):
        self.commands.append(argv)
        assert argv[0] == 'kubectl', argv
        args = argv[argv.index('-n') + 2:]
        if args[0] == 'apply':
            obj = json.loads(stdin)
            if obj['kind'] == 'Pod':
                name = obj['metadata']['name']
                obj['status'] = {'phase': 'Running',
                                 'podIP': f'10.4.0.{len(self.pods) + 1}'}
                self.pods[name] = obj
            else:
                self.services[obj['metadata']['name']] = obj
            return self._done()
        if args[0] == 'get' and args[1] == 'pod':
            name = args[2]
            if name in self.pods:
                if '-o' in args and args[args.index('-o') + 1] == 'json':
                    return self._done(0, json.dumps(self.pods[name]))
                return self._done(0, f'pod/{name}')
            return self._done(1, stderr='not found')
        if args[0] == 'get' and args[1] == 'pods':
            label = args[args.index('-l') + 1]
            cluster = label.split('=')[1]
            items = [p for p in self.pods.values()
                     if p['metadata']['labels'].get('skytpu-cluster') ==
                     cluster]
            return self._done(0, json.dumps({'items': items}))
        if args[0] == 'delete' and args[1] == 'pods':
            label = args[args.index('-l') + 1]
            cluster = label.split('=')[1]
            self.pods = {
                n: p for n, p in self.pods.items()
                if p['metadata']['labels'].get('skytpu-cluster') != cluster}
            return self._done()
        if args[0] == 'delete' and args[1] == 'pod':
            self.pods.pop(args[2], None)
            return self._done()
        if args[0] == 'delete' and args[1] == 'service':
            self.services.pop(args[2], None)
            return self._done()
        raise AssertionError(argv)

    @staticmethod
    def _done(rc=0, stdout='', stderr=''):
        return subprocess.CompletedProcess([], rc, stdout=stdout,
                                           stderr=stderr)


@pytest.fixture()
def fake_cli(monkeypatch):
    cli = FakeKubectl()
    monkeypatch.setattr(k8s, '_run_cli', cli)
    yield cli


def _config(cluster='kc1', hosts=2, gpus=0, gpu_label=None,
            context='kind-test'):
    return pcommon.ProvisionConfig(
        provider_name='kubernetes', cluster_name=cluster,
        region=context, zones=[context], count=hosts,
        deploy_vars={
            'tpu': False,
            'cpus': 4,
            'memory_gb': 16,
            'gpus': gpus,
            'gpu_type': 'A100' if gpus else None,
            'gpu_resource_key': 'nvidia.com/gpu',
            'gpu_label': gpu_label,
            'image_id': None,
            'namespace': 'default',
            'context': context,
        })


class TestKubernetesProvision:

    def test_create_pods(self, fake_cli):
        record = k8s.run_instances(_config())
        assert record.created_instance_ids == ['kc1-host0', 'kc1-host1']
        pod = fake_cli.pods['kc1-host0']
        requests = pod['spec']['containers'][0]['resources']['requests']
        assert requests == {'cpu': '4', 'memory': '16Gi'}
        assert 'nodeSelector' not in pod['spec']

        k8s.wait_instances('kc1')
        info = k8s.get_cluster_info('kc1')
        assert info.num_hosts == 2
        assert [i.worker_id for i in info.instances] == [0, 1]
        runners = k8s.get_command_runners(info)
        assert runners[0].pod_name == 'kc1-host0'

    def test_gpu_requests_and_node_selector(self, fake_cli):
        k8s.run_instances(_config(
            gpus=4, gpu_label='accel=nvidia-a100'))
        pod = fake_cli.pods['kc1-host0']
        res = pod['spec']['containers'][0]['resources']
        assert res['requests']['nvidia.com/gpu'] == '4'
        assert res['limits']['nvidia.com/gpu'] == '4'
        assert pod['spec']['nodeSelector'] == {'accel': 'nvidia-a100'}

    def test_idempotent(self, fake_cli):
        k8s.run_instances(_config())
        record = k8s.run_instances(_config())
        assert record.created_instance_ids == []
        assert record.resumed_instance_ids == ['kc1-host0', 'kc1-host1']

    def test_terminal_phase_pod_recreated(self, fake_cli):
        """A Failed pod (restartPolicy: Never) is deleted and recreated
        on relaunch, not 'resumed' into a permanently wedged cluster."""
        k8s.run_instances(_config())
        fake_cli.pods['kc1-host1']['status']['phase'] = 'Failed'
        record = k8s.run_instances(_config())
        assert record.resumed_instance_ids == ['kc1-host0']
        assert record.created_instance_ids == ['kc1-host1']
        assert fake_cli.pods['kc1-host1']['status']['phase'] == 'Running'
        k8s.wait_instances('kc1')

    def test_unknown_phase_is_transient_not_terminal(self, fake_cli):
        """'Unknown' (node partition) self-heals; the pod must be
        resumed, not deleted/recreated."""
        k8s.run_instances(_config())
        fake_cli.pods['kc1-host1']['status']['phase'] = 'Unknown'
        record = k8s.run_instances(_config())
        assert 'kc1-host1' in record.resumed_instance_ids

    def test_terminate_failure_keeps_meta(self, fake_cli, monkeypatch):
        """If kubectl delete fails, the meta record must survive so
        termination can be retried (else pods leak unrecoverably)."""
        k8s.run_instances(_config())

        def broken(argv, stdin=None):
            if 'delete' in argv and 'pods' in argv:
                return subprocess.CompletedProcess(
                    argv, 1, stdout='', stderr='apiserver unreachable')
            return fake_cli(argv, stdin)

        monkeypatch.setattr(k8s, '_run_cli', broken)
        with pytest.raises(exceptions.ProvisionError):
            k8s.terminate_instances('kc1')
        monkeypatch.setattr(k8s, '_run_cli', fake_cli)
        k8s.terminate_instances('kc1')  # retry succeeds
        assert k8s.query_instances('kc1') == {}

    def test_query_terminate(self, fake_cli):
        k8s.run_instances(_config())
        assert k8s.query_instances('kc1') == {
            'kc1-host0': ClusterStatus.UP, 'kc1-host1': ClusterStatus.UP}
        k8s.terminate_instances('kc1')
        assert fake_cli.pods == {}
        assert k8s.query_instances('kc1') == {}

    def test_terminate_worker_only(self, fake_cli):
        k8s.run_instances(_config())
        k8s.terminate_instances('kc1', worker_only=True)
        assert set(fake_cli.pods) == {'kc1-host0'}

    def test_stop_rejected(self, fake_cli):
        k8s.run_instances(_config())
        with pytest.raises(exceptions.NotSupportedError):
            k8s.stop_instances('kc1')

    def test_ports(self, fake_cli):
        k8s.run_instances(_config())
        k8s.open_ports('kc1', [8000])
        svc = fake_cli.services['kc1-svc']
        assert svc['spec']['ports'][0]['port'] == 8000
        assert svc['spec']['selector']['skytpu-host'] == '0'
        k8s.cleanup_ports('kc1')
        assert fake_cli.services == {}

    def test_context_pinned(self, fake_cli):
        k8s.run_instances(_config())
        for cmd in fake_cli.commands:
            assert cmd[cmd.index('--context') + 1] == 'kind-test'

    def test_query_raises_on_kubectl_failure(self, fake_cli, monkeypatch):
        k8s.run_instances(_config())

        def broken(argv, stdin=None):
            if 'get' in argv and 'pods' in argv:
                return subprocess.CompletedProcess(
                    argv, 1, stdout='', stderr='connection refused')
            return fake_cli(argv, stdin)

        monkeypatch.setattr(k8s, '_run_cli', broken)
        with pytest.raises(exceptions.ClusterStatusFetchingError):
            k8s.query_instances('kc1')

    def test_wait_fails_fast_on_terminal_pod(self, fake_cli):
        k8s.run_instances(_config())
        fake_cli.pods['kc1-host1']['status']['phase'] = 'Failed'
        with pytest.raises(exceptions.ProvisionError, match='terminal'):
            k8s.wait_instances('kc1')


class TestKubernetesCloud:

    def test_instance_type_grammar(self):
        from skypilot_tpu.clouds import kubernetes as kcloud
        assert kcloud.make_instance_type(4, 16) == 'k8s-4cpu-16gb'
        assert kcloud.parse_instance_type('k8s-4cpu-16gb') == (4, 16)
        assert kcloud.parse_instance_type('n1-standard-8') is None

    def test_feasibility_cpu(self):
        from skypilot_tpu import Resources
        from skypilot_tpu.clouds import registry
        cloud = registry.from_str('kubernetes')
        launchable, _ = cloud.get_feasible_launchable_resources(
            Resources(cloud='kubernetes', cpus='8+', memory='32'))
        assert len(launchable) == 1
        assert launchable[0].instance_type == 'k8s-8cpu-32gb'
        assert launchable[0].get_cost(3600) == 0.0

    def test_feasibility_rejects_tpu_and_spot(self):
        from skypilot_tpu import Resources
        from skypilot_tpu.clouds import registry
        cloud = registry.from_str('k8s')  # alias resolves
        tpus, _ = cloud.get_feasible_launchable_resources(
            Resources(accelerators='tpu-v5e-8'))
        assert tpus == []
        spot, _ = cloud.get_feasible_launchable_resources(
            Resources(cloud='kubernetes', use_spot=True))
        assert spot == []

    def test_gpu_deploy_vars(self, monkeypatch, _isolated_home):
        from skypilot_tpu import Resources
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.clouds import registry
        cfg_path = _isolated_home / 'config.yaml'
        cfg_path.write_text('kubernetes:\n  context: kind-test\n'
                            '  namespace: ml\n')
        monkeypatch.setenv('SKYTPU_CONFIG', str(cfg_path))
        config_lib.reload_config()
        try:
            cloud = registry.from_str('kubernetes')
            resources = Resources(cloud='kubernetes',
                                  accelerators={'A100': 2})
            launchable, _ = cloud.get_feasible_launchable_resources(
                resources)
            assert launchable
            region = cloud.regions_with_offering(resources)[0]
            assert region.name == 'kind-test'
            deploy = cloud.make_deploy_resources_variables(
                launchable[0], 'c1', region, region.zones)
            assert deploy['gpus'] == 2
            assert deploy['gpu_type'] == 'A100'
            assert deploy['namespace'] == 'ml'
            assert deploy['context'] == 'kind-test'
        finally:
            config_lib.reload_config()
