"""Checkpoint contract tests (ISSUE 6): restore_or_init round trip,
async-vs-sync save equivalence, retry behavior under an injected
``checkpoint.save`` fault, and the restore_params/restore_sharded
validation paths."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.chaos import faults as faults_lib
from skypilot_tpu.chaos import injector
from skypilot_tpu.data import checkpoints
from skypilot_tpu.models import configs
from skypilot_tpu.models import train as train_lib
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.parallel import mesh as mesh_lib


def _tiny_state():
    cfg = configs.get_config('tiny')
    state, _ = train_lib.create_train_state(cfg, batch_size=4, seq_len=16)
    return cfg, state


def _leaves_allclose(a, b):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    return all(np.allclose(x, y) for x, y in zip(la, lb))


def test_restore_or_init_round_trip(tmp_path):
    """save → restore → start_step: the auto-resume convention."""
    _, state = _tiny_state()
    directory = str(tmp_path / 'ckpt')
    mgr = checkpoints.AsyncCheckpointManager(directory,
                                             save_interval_steps=1)
    assert mgr.save(7, state)
    mgr.close()
    assert mgr.latest_step() == 7

    mgr2 = checkpoints.AsyncCheckpointManager(directory)
    restored, start_step = mgr2.restore_or_init(state)
    assert start_step == 8
    assert _leaves_allclose(state, restored)
    mgr2.close()


def test_restore_or_init_no_checkpoint(tmp_path):
    _, state = _tiny_state()
    mgr = checkpoints.AsyncCheckpointManager(str(tmp_path / 'none'))
    same, start_step = mgr.restore_or_init(state)
    assert start_step == 0
    assert same is state
    mgr.close()


def test_async_and_sync_saves_are_equivalent(tmp_path):
    """A restored async save must be tree-allclose to a restored
    blocking save of the same state — async moves the write off the
    step path, never changes what lands on disk."""
    _, state = _tiny_state()
    async_dir = str(tmp_path / 'async')
    sync_dir = str(tmp_path / 'sync')
    with checkpoints.AsyncCheckpointManager(async_dir,
                                            async_save=True) as amgr:
        amgr.save(3, state)
    with checkpoints.AsyncCheckpointManager(sync_dir,
                                            async_save=False) as smgr:
        smgr.save(3, state)
    a, a_step = checkpoints.AsyncCheckpointManager(
        async_dir).restore_or_init(state)
    s, s_step = checkpoints.AsyncCheckpointManager(
        sync_dir).restore_or_init(state)
    assert a_step == s_step == 4
    assert _leaves_allclose(a, s)
    assert _leaves_allclose(a, state)


def test_save_interval_skips_off_interval_steps(tmp_path):
    _, state = _tiny_state()
    with checkpoints.AsyncCheckpointManager(
            str(tmp_path / 'ckpt'), save_interval_steps=3) as mgr:
        assert mgr.save(0, state)
        assert not mgr.save(1, state)
        assert not mgr.save(2, state)
        assert mgr.save(3, state)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3


def test_save_retries_through_injected_fault(tmp_path):
    """A bucket-write flake (chaos checkpoint.save raise) is retried
    with backoff and the save still lands; the journal records the
    attempt count."""
    _, state = _tiny_state()
    journal = events_lib.training_journal()
    plan = faults_lib.FaultPlan(seed=0, faults=[faults_lib.Fault(
        site='checkpoint.save', effect='raise', error='OSError',
        nth=[1])])
    injector.arm(plan)
    try:
        with checkpoints.AsyncCheckpointManager(
                str(tmp_path / 'ckpt'), max_retries=3,
                retry_backoff_s=0.01, journal=journal) as mgr:
            mgr.save(0, state)
            mgr.wait_until_finished()
            assert mgr.saves_ok == 1
            assert mgr.saves_failed == 0
            assert mgr.latest_step() == 0
    finally:
        injector.disarm()
    ends = [e for e in journal.tail()
            if e.get('event') == 'checkpoint_save_end']
    assert ends and ends[-1]['status'] == 'ok'
    assert ends[-1]['attempts'] == 2


def test_save_exhausts_retries_without_killing_training(tmp_path):
    """Retry exhaustion journals the failure and training continues —
    a flaky bucket degrades checkpoint freshness, never kills the
    run."""
    _, state = _tiny_state()
    journal = events_lib.training_journal()
    plan = faults_lib.FaultPlan(seed=0, faults=[faults_lib.Fault(
        site='checkpoint.save', effect='raise', error='OSError')])
    injector.arm(plan)
    try:
        with checkpoints.AsyncCheckpointManager(
                str(tmp_path / 'ckpt'), max_retries=1,
                retry_backoff_s=0.01, journal=journal) as mgr:
            mgr.save(0, state)
            mgr.wait_until_finished()
            assert mgr.saves_failed == 1
            assert isinstance(mgr.last_error, OSError)
            # The step loop keeps going: another save schedules fine.
            mgr.save(1, state)
    finally:
        injector.disarm()
    ends = [e for e in journal.tail()
            if e.get('event') == 'checkpoint_save_end']
    assert any(e['status'] == 'OSError' and e['attempts'] == 2
               for e in ends)


def test_restore_params_leaf_count_mismatch_raises(tmp_path):
    """A shardings tree whose leaf count mismatches the checkpoint's
    params subtree used to die with a bare StopIteration; now it's a
    ValueError naming both counts."""
    _, state = _tiny_state()
    directory = str(tmp_path / 'ckpt')
    with checkpoints.AsyncCheckpointManager(directory) as mgr:
        mgr.save(0, state)
    n_params = len(jax.tree_util.tree_leaves(state.params))
    device = jax.devices()[0]
    bad_shardings = [jax.sharding.SingleDeviceSharding(device)] * 3
    with pytest.raises(ValueError, match=f'3 leaves.*{n_params}'):
        checkpoints.restore_params(directory, shardings=bad_shardings)


def test_restore_sharded_onto_smaller_mesh(tmp_path):
    """The elastic restore: a checkpoint saved on an 8-device mesh
    streams onto a 4-device mesh's shardings, numerically intact."""
    cfg = configs.get_config('tiny')
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip('needs 8 virtual devices')
    mesh8 = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8),
                                devices=devices)
    state, _ = train_lib.create_train_state(cfg, mesh=mesh8,
                                            batch_size=8, seq_len=16)
    directory = str(tmp_path / 'ckpt')
    with checkpoints.AsyncCheckpointManager(directory) as mgr:
        mgr.save(5, state)

    mesh4 = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=4),
                                devices=devices[:4])
    abstract, shardings = train_lib.abstract_train_state(
        cfg, mesh=mesh4, batch_size=8, seq_len=16)
    restored, start_step = checkpoints.restore_sharded(
        directory, abstract, shardings)
    assert start_step == 6
    assert _leaves_allclose(state, restored)
    param_leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert len(param_leaf.sharding.device_set) <= 4


def test_restore_sharded_empty_dir(tmp_path):
    cfg = configs.get_config('tiny')
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=-1))
    abstract, shardings = train_lib.abstract_train_state(
        cfg, mesh=mesh, batch_size=8, seq_len=16)
    state, step = checkpoints.restore_sharded(
        str(tmp_path / 'missing'), abstract, shardings)
    assert state is None and step == 0


def test_blocked_in_flight_accounting(tmp_path, monkeypatch):
    """With max_in_flight=1 and a slow write, the second save blocks
    and the blocked time is accounted (the signal that the save
    interval is shorter than the write)."""
    import orbax.checkpoint as ocp
    import time as time_mod
    _, state = _tiny_state()
    real_save = ocp.CheckpointManager.save

    def slow_save(self, *args, **kwargs):
        time_mod.sleep(0.2)
        return real_save(self, *args, **kwargs)

    monkeypatch.setattr(ocp.CheckpointManager, 'save', slow_save)
    with checkpoints.AsyncCheckpointManager(
            str(tmp_path / 'ckpt'), max_in_flight=1) as mgr:
        mgr.save(0, state)
        mgr.save(1, state)
        mgr.wait_until_finished()
    assert mgr.blocked_seconds > 0.05
