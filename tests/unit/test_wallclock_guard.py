"""Unit tests for the tier-1 wall-clock guard (ISSUE 9 satellite):
conftest fails a FULL tier-1 run that crosses the trip fraction of the
870s timeout budget, naming the top-10 slowest tests."""
from __future__ import annotations

import importlib.util
import pathlib


def _load_conftest():
    path = pathlib.Path(__file__).parents[1] / 'conftest.py'
    spec = importlib.util.spec_from_file_location('_t1_conftest', path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_conftest = _load_conftest()
_guard = _conftest.tier1_wallclock_violation


def test_within_budget_is_clean():
    assert _guard(500.0, 800, {'a': 1.0}, budget_s=870.0) is None


def test_partial_run_never_trips():
    # A dev loop running one file must not be failed for slowness.
    assert _guard(5000.0, 12, {'a': 1.0}, budget_s=870.0) is None


def test_over_threshold_trips_with_top10():
    durations = {f'tests/unit/test_x.py::t{i}': float(i)
                 for i in range(1, 25)}
    msg = _guard(860.0, 800, durations, budget_s=870.0)
    assert msg is not None
    assert 'Top 10 slowest' in msg
    # The worst offender leads the report; the 10 slowest are named,
    # the 14 fastest are not.
    assert 't24' in msg and 't15' in msg
    assert 't14' not in msg
    assert '870' in msg


def test_threshold_is_the_trip_fraction():
    # 0.92 * 870 = 800.4: just under stays green, just over trips.
    assert _guard(800.0, 800, {}, budget_s=870.0) is None
    assert _guard(801.0, 800, {}, budget_s=870.0) is not None


def test_budget_override():
    assert _guard(300.0, 800, {}, budget_s=200.0) is not None
    assert _guard(300.0, 800, {}, budget_s=2000.0) is None
