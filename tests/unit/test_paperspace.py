"""Paperspace cloud + machines-API provisioner (cloud breadth).  The
REST API sits behind an injectable transport
(provision/paperspace/instance.py: set_api_runner).  Unlike
Lambda/RunPod, Paperspace machines stop/start for real, so the
resume path is exercised too.  Model: tests/unit/test_lambda_cloud.py.
"""
from __future__ import annotations

import json

import pytest

import skypilot_tpu as sky
from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import registry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.paperspace import instance as ps_instance


class FakePaperspaceApi:
    """Minimal machines-API state machine."""

    def __init__(self):
        self.machines = {}   # id -> machine dict
        self.calls = []
        self._next = 0
        self.fail_after = None   # create N machines then 400

    def __call__(self, method, path, payload):
        self.calls.append((method, path, payload))
        if method == 'GET' and path.startswith('/machines'):
            return 200, {'items': list(self.machines.values())}
        if (method, path) == ('POST', '/machines'):
            if (self.fail_after is not None and
                    len(self.machines) >= self.fail_after):
                return 400, {'message': 'machine quota exceeded'}
            mid = f'ps-{self._next:05d}'
            self._next += 1
            self.machines[mid] = {
                'id': mid,
                'name': payload['name'],
                'state': 'ready',
                'region': payload['region'],
                'machineType': payload['machineType'],
                'publicIp': f'172.8.0.{self._next}',
                'privateIp': f'10.5.0.{self._next}',
                '_input': payload,
            }
            return 200, {'data': {'id': mid}}
        if method == 'PATCH' and path.endswith('/stop'):
            mid = path.split('/')[2]
            self.machines[mid]['state'] = 'off'
            return 200, {}
        if method == 'PATCH' and path.endswith('/start'):
            mid = path.split('/')[2]
            self.machines[mid]['state'] = 'ready'
            return 200, {}
        if method == 'DELETE':
            self.machines.pop(path.split('/')[2], None)
            return 200, {}
        return 404, {'message': f'unhandled {method} {path}'}


@pytest.fixture
def fake_api():
    api = FakePaperspaceApi()
    ps_instance.set_api_runner(api)
    yield api
    ps_instance.set_api_runner(None)


def _config(cluster='psc', count=2, itype='A100-80G'):
    return provision_common.ProvisionConfig(
        provider_name='paperspace', cluster_name=cluster,
        region='East Coast (NY2)', zones=[],
        deploy_vars={'instance_type': itype, 'disk_size': 100},
        count=count)


class TestProvisionLifecycle:

    def test_create_query_info_terminate(self, fake_api):
        record = ps_instance.run_instances(_config())
        assert record.provider_name == 'paperspace'
        assert len(record.created_instance_ids) == 2
        names = sorted(m['name'] for m in fake_api.machines.values())
        assert names == ['psc-0', 'psc-1']
        inp = next(iter(fake_api.machines.values()))['_input']
        assert inp['machineType'] == 'A100-80G'
        # Our public key is installed via the startup script.
        assert 'authorized_keys' in inp['startupScript']

        status = ps_instance.query_instances('psc')
        assert all(s.value == 'UP' for s in status.values())

        info = ps_instance.get_cluster_info('psc')
        assert info.ssh_user == 'paperspace'
        assert [i.tags['rank'] for i in info.instances] == ['0', '1']
        assert info.instances[0].external_ip.startswith('172.8.')

        ps_instance.terminate_instances('psc')
        assert ps_instance.query_instances('psc') == {}

    def test_stop_start_resume(self, fake_api):
        ps_instance.run_instances(_config())
        ps_instance.stop_instances('psc')
        status = ps_instance.query_instances('psc')
        assert all(s.value == 'STOPPED' for s in status.values())
        record = ps_instance.run_instances(_config())
        assert len(record.resumed_instance_ids) == 2
        status = ps_instance.query_instances('psc')
        assert all(s.value == 'UP' for s in status.values())

    def test_count_mismatch_rejected(self, fake_api):
        ps_instance.run_instances(_config(count=2))
        with pytest.raises(exceptions.ResourcesMismatchError):
            ps_instance.run_instances(_config(count=3))

    def test_partial_create_sweeps(self, fake_api):
        fake_api.fail_after = 1
        with pytest.raises(exceptions.ProvisionError,
                           match='quota exceeded'):
            ps_instance.run_instances(_config(count=2))
        assert fake_api.machines == {}

    def test_worker_only_stop_keeps_head(self, fake_api):
        ps_instance.run_instances(_config(count=3))
        ps_instance.stop_instances('psc', worker_only=True)
        states = {m['name']: m['state']
                  for m in fake_api.machines.values()}
        assert states == {'psc-0': 'ready', 'psc-1': 'off',
                          'psc-2': 'off'}

    def test_name_prefix_does_not_cross_clusters(self, fake_api):
        """Cluster 'psc' must not see machines of cluster 'psc-extra'
        (both share a name prefix)."""
        ps_instance.run_instances(_config(cluster='psc', count=1))
        ps_instance.run_instances(_config(cluster='psc-extra', count=1))
        assert len(ps_instance.query_instances('psc')) == 1
        assert len(ps_instance.query_instances('psc-extra')) == 1

    def test_foreign_machine_with_nonnumeric_suffix_ignored(self,
                                                            fake_api):
        """A user's hand-made 'psc-head' machine must neither crash
        rank parsing nor be terminated by our sweep (review finding)."""
        fake_api.machines['alien'] = {
            'id': 'alien', 'name': 'psc-head', 'state': 'ready',
            'publicIp': '1.2.3.4', 'privateIp': '10.0.0.9',
        }
        ps_instance.run_instances(_config(cluster='psc', count=1))
        assert len(ps_instance.query_instances('psc')) == 1
        ps_instance.terminate_instances('psc')
        assert 'alien' in fake_api.machines  # untouched

    def test_disk_size_rounds_to_valid_tier(self, fake_api):
        """Paperspace only accepts fixed disk tiers; the framework
        default of 256 must round up to 500, not 400 on create."""
        cfg = _config(count=1)
        cfg.deploy_vars['disk_size'] = 256
        ps_instance.run_instances(cfg)
        inp = next(iter(fake_api.machines.values()))['_input']
        assert inp['diskSize'] == 500

    def test_transitional_states_never_read_as_gone(self, fake_api):
        """'restarting'/'serviceready' machines exist and bill; mapping
        them to None would make the status layer remove the cluster
        record while machines keep running (review finding)."""
        ps_instance.run_instances(_config(count=1))
        machine = next(iter(fake_api.machines.values()))
        for state in ('serviceready', 'restarting', 'upgrading',
                      'error', 'provisioning'):
            machine['state'] = state
            statuses = ps_instance.query_instances('psc')
            assert list(statuses.values())[0] is not None, state

    def test_sweep_is_best_effort(self, fake_api):
        """A failing DELETE during the partial-create sweep must not
        mask the original create error."""
        fake_api.fail_after = 1
        orig = fake_api.__call__

        def flaky(method, path, payload):
            if method == 'DELETE':
                return 429, {'message': 'rate limited'}
            return orig(method, path, payload)

        fake_api_call = fake_api.__class__.__call__
        fake_api.__class__.__call__ = lambda self, m, p, d: flaky(m, p, d)
        try:
            with pytest.raises(exceptions.ProvisionError,
                               match='quota exceeded'):
                ps_instance.run_instances(_config(count=2))
        finally:
            fake_api.__class__.__call__ = fake_api_call


class TestPaperspaceCloud:

    def test_feasibility_and_pricing(self):
        ps = registry.CLOUD_REGISTRY['paperspace']
        r = sky.Resources(cloud='paperspace', accelerators='A100-80GB:8')
        launchable, _ = ps.get_feasible_launchable_resources(r)
        assert launchable
        assert launchable[0].instance_type == 'A100-80Gx8'
        assert catalog.get_hourly_cost(
            'paperspace', 'A100-80G') == pytest.approx(3.18)

    def test_tpu_and_spot_not_feasible(self):
        ps = registry.CLOUD_REGISTRY['paperspace']
        assert ps.get_feasible_launchable_resources(
            sky.Resources(accelerators='tpu-v5e-8'))[0] == []
        spot = sky.Resources(cloud='paperspace', accelerators='A100:1',
                             capacity='spot')
        assert ps.get_feasible_launchable_resources(spot)[0] == []

    def test_stop_supported(self):
        """Unlike Lambda/RunPod, STOP is NOT gated: autostop works."""
        from skypilot_tpu.clouds import cloud as cloud_lib
        ps = registry.CLOUD_REGISTRY['paperspace']
        ps.check_features_are_supported(
            sky.Resources(cloud='paperspace'),
            {cloud_lib.CloudImplementationFeatures.STOP})

    def test_credentials_from_config_json(self, tmp_path, monkeypatch):
        monkeypatch.setenv('HOME', str(tmp_path))
        monkeypatch.delenv('PAPERSPACE_API_KEY', raising=False)
        ps = registry.CLOUD_REGISTRY['paperspace']
        ok, reason = ps.check_credentials()
        assert not ok and 'config.json' in reason
        cfg = tmp_path / '.paperspace'
        cfg.mkdir()
        (cfg / 'config.json').write_text(
            json.dumps({'apiKey': 'psk-12345678'}))
        ok, _ = ps.check_credentials()
        assert ok
        assert ps.get_current_user_identity() == ['paperspace:psk-1234']
