"""Decode-kernel and speculative-decoding tests (CPU interpreter
mode): the Pallas paged-attention kernel vs the gather view vs the
dense reference must be token-exact, greedy and sampled, bf16 and
int8 pages, aligned and misaligned prompts — and self-speculative
decoding must be byte-identical to plain decoding with acceptance
visible in stats/spans.

Kernel choice is resolved ONCE at engine construction
(`SKYTPU_DECODE_KERNEL`, default pallas wherever Pallas can run), so
fixtures pin the env only around construction.  Engines are
module-scoped: every instance re-jits the paged step."""
from __future__ import annotations

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import configs
from skypilot_tpu.models import decode
from skypilot_tpu.models.transformer import Transformer
from skypilot_tpu.ops import paged_attention
from skypilot_tpu.serve import batching_engine
from skypilot_tpu.serve import sampler as sampler_lib

# Misaligned on purpose: lengths 7 and 13 straddle neither the page
# (8) nor the chunk (8) boundary; 24 is multi-page aligned; 1 is the
# empty-prefill edge.
PROMPTS = (([3, 1, 4, 1, 5, 9, 2, 6], 6),
           ([7], 4),
           ([2, 7, 1, 8, 2, 8, 1], 7),
           (list(range(5, 18)), 5),
           (list(range(1, 25)), 5))


@pytest.fixture(scope='module')
def setup():
    cfg = configs.get_config('tiny')
    model = Transformer(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))['params'])
    return cfg, params


def _reference(cfg, params, prompt_ids, n, max_len=64):
    prompt = jnp.asarray([prompt_ids], jnp.int32)
    _, new = decode.generate(cfg, params, prompt, max_new_tokens=n,
                             max_len=max_len)
    return [int(t) for t in np.asarray(new)[0]]


def _engine(cfg, params, *, kernel=None, **kw):
    """Build a paged engine with the decode kernel pinned via env for
    the duration of construction (where the choice is baked)."""
    kw.setdefault('max_len', 64)
    kw.setdefault('slots', 2)
    kw.setdefault('prefill_chunk', 8)
    kw.setdefault('kv_pages', 48)
    kw.setdefault('page_size', 8)
    saved = {k: os.environ.get(k) for k in
             ('SKYTPU_DECODE_KERNEL', 'SKYTPU_PALLAS_INTERPRET')}
    try:
        if kernel == 'pallas':
            os.environ['SKYTPU_DECODE_KERNEL'] = 'pallas'
            os.environ['SKYTPU_PALLAS_INTERPRET'] = '1'
        elif kernel == 'gather':
            os.environ['SKYTPU_DECODE_KERNEL'] = 'gather'
        return batching_engine.ContinuousBatchingEngine(cfg, params,
                                                        **kw)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture(scope='module')
def gather_engine(setup):
    cfg, params = setup
    eng = _engine(cfg, params, kernel='gather')
    yield eng
    eng.stop()


@pytest.fixture(scope='module')
def pallas_engine(setup):
    cfg, params = setup
    eng = _engine(cfg, params, kernel='pallas')
    yield eng
    eng.stop()


@pytest.fixture(scope='module')
def spec_engine(setup):
    cfg, params = setup
    eng = _engine(cfg, params, kernel='gather', spec_tokens=3)
    yield eng
    eng.stop()


class TestKernelChoice:

    def test_default_off_tpu_is_gather(self, monkeypatch):
        monkeypatch.delenv('SKYTPU_DECODE_KERNEL', raising=False)
        monkeypatch.delenv('SKYTPU_PALLAS_INTERPRET', raising=False)
        assert paged_attention.decode_kernel_choice() == 'gather'

    def test_interpret_mode_defaults_to_pallas(self, monkeypatch):
        monkeypatch.delenv('SKYTPU_DECODE_KERNEL', raising=False)
        monkeypatch.setenv('SKYTPU_PALLAS_INTERPRET', '1')
        assert paged_attention.decode_kernel_choice() == 'pallas'

    def test_explicit_pin_wins(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_PALLAS_INTERPRET', '1')
        monkeypatch.setenv('SKYTPU_DECODE_KERNEL', 'gather')
        assert paged_attention.decode_kernel_choice() == 'gather'
        monkeypatch.delenv('SKYTPU_PALLAS_INTERPRET', raising=False)
        monkeypatch.setenv('SKYTPU_DECODE_KERNEL', 'pallas')
        assert paged_attention.decode_kernel_choice() == 'pallas'

    def test_invalid_choice_rejected(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_DECODE_KERNEL', 'fused9000')
        with pytest.raises(ValueError, match='SKYTPU_DECODE_KERNEL'):
            paged_attention.decode_kernel_choice()

    def test_engine_reports_kernel(self, gather_engine, pallas_engine):
        assert gather_engine.decode_kernel == 'gather'
        assert pallas_engine.decode_kernel == 'pallas'
        assert gather_engine.stats()['decode_kernel'] == 'gather'
        assert pallas_engine.stats()['decode_kernel'] == 'pallas'


class TestPallasKernelParity:

    def test_greedy_parity_vs_dense_reference(self, setup,
                                              pallas_engine):
        """The in-kernel block-table read must reproduce the dense
        reference token-for-token, including prompts that straddle
        page and chunk boundaries."""
        cfg, params = setup
        for prompt, n in PROMPTS:
            got = pallas_engine.generate(prompt, n, timeout=180)
            assert got == _reference(cfg, params, prompt, n), prompt

    def test_greedy_parity_pallas_vs_gather(self, gather_engine,
                                            pallas_engine):
        """Both paged paths attend over the same pages with the same
        masking math — outputs must be identical, not just close."""
        for prompt, n in PROMPTS:
            a = gather_engine.generate(prompt, n, timeout=180)
            b = pallas_engine.generate(prompt, n, timeout=180)
            assert a == b, prompt

    def test_sampled_parity_pallas_vs_gather(self, gather_engine,
                                             pallas_engine):
        """Sampling depends only on (logits, key chain): at a fixed
        seed the kernel choice must not change a single token."""
        sampling = decode.SamplingConfig(temperature=0.8, top_k=10,
                                         seed=123)
        prompt = [3, 1, 4, 1, 5, 9, 2]
        a = gather_engine.generate(prompt, 6, sampling=sampling,
                                   timeout=180)
        b = pallas_engine.generate(prompt, 6, sampling=sampling,
                                   timeout=180)
        assert a == b

    def test_int8_pages_greedy_parity(self, setup):
        """Fused in-kernel dequant must agree with the gather path's
        dequant-then-attend on int8 pools."""
        cfg, params = setup
        eng_p = _engine(cfg, params, kernel='pallas', quantize_kv=True)
        eng_g = _engine(cfg, params, kernel='gather', quantize_kv=True)
        try:
            for prompt, n in (([3, 1, 4, 1, 5, 9, 2, 6], 6),
                              ([2, 7, 1, 8, 2, 8, 1], 5)):
                assert (eng_p.generate(prompt, n, timeout=180) ==
                        eng_g.generate(prompt, n, timeout=180)), prompt
        finally:
            eng_p.stop()
            eng_g.stop()

    def test_kernel_gauge_tracks_choice(self, setup):
        from skypilot_tpu.observability import metrics as metrics_lib
        cfg, params = setup
        eng = _engine(cfg, params, kernel='pallas')
        try:
            text = metrics_lib.expose()
            assert 'skytpu_engine_decode_kernel_pallas 1' in text
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_greedy_sweep_misaligned_lengths(self, setup,
                                             gather_engine,
                                             pallas_engine):
        """Every prompt length across a page of offsets: the online-
        softmax accumulation over table rows must be exact wherever
        the write cursor lands within a page."""
        cfg, params = setup
        for plen in range(1, 18):
            prompt = [(7 * i + 3) % (cfg.vocab_size - 2) + 1
                      for i in range(plen)]
            ref = _reference(cfg, params, prompt, 4)
            assert gather_engine.generate(
                prompt, 4, timeout=180) == ref, plen
            assert pallas_engine.generate(
                prompt, 4, timeout=180) == ref, plen


class TestSpeculativeDecoding:

    def test_greedy_byte_identity_spec_on_vs_off(self, setup,
                                                 gather_engine,
                                                 spec_engine):
        """The acceptance rule (longest exact prefix + bonus token)
        makes speculation invisible in outputs — byte-identical to
        sequential greedy on every prompt shape."""
        del setup
        for prompt, n in PROMPTS:
            a = gather_engine.generate(prompt, n, timeout=180)
            b = spec_engine.generate(prompt, n, timeout=180)
            assert a == b, prompt

    def test_sampled_seed_identity_spec_on_vs_off(self, gather_engine,
                                                  spec_engine):
        """The key chain advances once per EMITTED token, so a fixed
        seed yields the same stream with speculation on or off."""
        sampling = decode.SamplingConfig(temperature=0.8, top_k=10,
                                         seed=123)
        prompt = [3, 1, 4, 1, 5, 9, 2]
        a = gather_engine.generate(prompt, 6, sampling=sampling,
                                   timeout=180)
        b = spec_engine.generate(prompt, 6, sampling=sampling,
                                 timeout=180)
        assert a == b

    def test_concurrent_spec_requests_exact(self, setup, spec_engine):
        cfg, params = setup
        prompts = [([3, 1, 4, 1, 5], 5), ([2, 7], 8),
                   ([9, 9, 8, 2, 1, 0, 3], 3)]
        requests = [spec_engine.submit(p, n) for p, n in prompts]
        for (p, n), r in zip(prompts, requests):
            assert r.result(timeout=180) == _reference(
                cfg, params, p, n), (p, n)

    def test_spec_stats_and_span_fields(self, spec_engine):
        spec_engine.generate(list(range(1, 20)), 8, timeout=180)
        st = spec_engine.stats()
        assert st['spec_tokens'] == 3
        assert st['spec_ticks'] > 0
        assert st['spec_proposed_tokens'] >= st['spec_accepted_tokens']
        assert st['spec_proposed_tokens'] > 0
        # 1.0 <= mean accept length <= k + 1 by construction.
        assert 1.0 <= st['spec_accept_len_mean'] <= 4.0
        span = st['recent_spans'][0]
        assert span['spec_steps'] > 0
        assert span['spec_accept_mean'] >= 1.0

    def test_spec_composes_with_pallas_and_int8(self, setup,
                                                gather_engine):
        cfg, params = setup
        eng = _engine(cfg, params, kernel='pallas', quantize_kv=True,
                      spec_tokens=3)
        try:
            assert eng.decode_kernel == 'pallas'
            for prompt, n in (([3, 1, 4, 1, 5, 9, 2, 6], 8),
                              ([2, 7, 1, 8, 2, 8, 1], 5)):
                ref = _engine(cfg, params, kernel='gather',
                              quantize_kv=True)
                try:
                    want = ref.generate(prompt, n, timeout=180)
                finally:
                    ref.stop()
                assert eng.generate(prompt, n, timeout=300) == want
        finally:
            eng.stop()

    def test_dense_engine_rejects_spec(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match='paged KV'):
            batching_engine.ContinuousBatchingEngine(cfg, params,
                                                     spec_tokens=2)

    def test_negative_spec_tokens_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            batching_engine.ContinuousBatchingEngine(
                cfg, params, kv_pages=48, page_size=8, max_len=64,
                spec_tokens=-1)

    @pytest.mark.slow
    def test_spec_sweep_prompt_shapes(self, setup, gather_engine,
                                      spec_engine):
        """Wider identity sweep: every length across a couple of page
        offsets, greedy, and a second seed for the sampled path."""
        del setup
        for plen in (1, 2, 7, 8, 9, 15, 16, 17, 24, 30):
            prompt = [(5 * i + 2) % 200 + 1 for i in range(plen)]
            a = gather_engine.generate(prompt, 6, timeout=180)
            b = spec_engine.generate(prompt, 6, timeout=180)
            assert a == b, plen
        sampling = decode.SamplingConfig(temperature=1.1, top_k=5,
                                         seed=7)
        prompt = list(range(3, 17))
        assert (gather_engine.generate(prompt, 8, sampling=sampling,
                                       timeout=180) ==
                spec_engine.generate(prompt, 8, sampling=sampling,
                                     timeout=180))


class TestNgramDrafter:

    def test_prompt_lookup_replays_continuation(self):
        d = sampler_lib.NgramDrafter([1, 2, 3, 9, 1, 2])
        # Tail bigram [1, 2] last occurred at index 0; the following
        # tokens are [3, 9] — exactly what prompt-lookup replays.
        assert d.propose(2) == [3, 9]

    def test_pads_with_last_token(self):
        d = sampler_lib.NgramDrafter([5])
        # No earlier occurrence to extend: pad with the last history
        # token (a valid vocab id — pads are embedded before the
        # verify tick rejects them).
        assert d.propose(3) == [5, 5, 5]

    def test_observe_extends_history(self):
        d = sampler_lib.NgramDrafter([4, 6])
        d.observe([4, 6])
        # History [4, 6, 4, 6]: tail [4, 6] matches at index 0 and
        # replays [4, 6] — the greedy-cycle case speculation feeds on.
        assert d.propose(2) == [4, 6]

    def test_match_prefers_longest_ngram(self):
        d = sampler_lib.NgramDrafter([1, 2, 3, 7, 2, 3, 8, 1, 2, 3])
        # Trigram [1, 2, 3] matches at index 0 (-> 7); the bigram
        # [2, 3] alone would have matched index 4 (-> 8) — longest
        # n-gram wins.
        assert d.propose(1) == [7]
