"""Slice-serving runtime tests (ISSUE 9).

The load-bearing claims:

- a 2-host EMULATED sharded replica (weights + KV pool over the slice
  mesh, coordinated ticks, sequence-parallel prefill) is TOKEN-EXACT
  against the single-process engine — float and int8-KV pools, greedy
  and sampled;
- the rank protocol degrades a slice AS A UNIT: one dead rank fails
  the engine, /health turns 503 with slice.degraded, and the replica
  manager retires the replica;
- the degenerate mesh fix (ops/sp_common): ring/ulysses attention run
  on a mesh whose sequence axis is size 1 — or absent — through the
  same code path (the regression the `num_hosts: 1` slice needs);
- `num_hosts` flows end to end: service_spec roles -> scale_up env ->
  serve_state column (additive migration; old DBs load cleanly).
"""
from __future__ import annotations

import socket
import sqlite3
import threading
import time

import pytest

from skypilot_tpu.serve import batching_engine
from skypilot_tpu.serve import coordinator as coordinator_lib
from skypilot_tpu.serve import slice_replica


@pytest.fixture(scope='module')
def tiny():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import configs
    from skypilot_tpu.models.transformer import Transformer
    cfg = configs.get_config('tiny')
    params = nn.meta.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))['params'])
    return cfg, params


_PROMPTS = [list(range(1, 49)),          # spans the sp threshold
            list(range(5, 70)),          # longer, odd length
            [3, 1, 4, 1, 5]]             # short (chunked path)


def _sampling():
    from skypilot_tpu.models import decode
    return decode.SamplingConfig(temperature=0.8, top_k=8, seed=7)


def _outputs(engine):
    """Greedy + sampled generations for the standard prompt set."""
    greedy = [engine.generate(p, 8, timeout=120) for p in _PROMPTS]
    sampled = [engine.generate(p, 8, sampling=_sampling(), timeout=120)
               for p in _PROMPTS]
    return greedy, sampled


# ------------------------------------------------------------ mesh layout


class TestSliceAxes:

    def test_default_prefers_tensor_then_sequence(self, tiny):
        cfg, _ = tiny                       # tiny: n_kv_heads=2
        assert slice_replica.slice_axes(1, cfg) == {
            'sequence': 1, 'tensor': 1}
        assert slice_replica.slice_axes(2, cfg) == {
            'sequence': 1, 'tensor': 2}
        # n_kv_heads=2 caps tensor at 2; the rest rides 'sequence'.
        assert slice_replica.slice_axes(4, cfg) == {
            'sequence': 2, 'tensor': 2}
        assert slice_replica.slice_axes(8, cfg) == {
            'sequence': 4, 'tensor': 2}

    def test_pinned_factors(self, tiny):
        cfg, _ = tiny
        assert slice_replica.slice_axes(4, cfg, sequence=4) == {
            'sequence': 4, 'tensor': 1}
        assert slice_replica.slice_axes(4, cfg, tensor=1) == {
            'sequence': 4, 'tensor': 1}
        with pytest.raises(ValueError, match='must equal'):
            slice_replica.slice_axes(4, cfg, sequence=2, tensor=3)
        with pytest.raises(ValueError, match='divide'):
            slice_replica.slice_axes(4, cfg, sequence=3)
        with pytest.raises(ValueError, match='n_kv_heads'):
            slice_replica.slice_axes(4, cfg, tensor=4)

    def test_mesh_device_bound(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match='devices'):
            slice_replica.build_slice_mesh(64, cfg)


# --------------------------------------------- degenerate sequence meshes


class TestSequenceParallelDegenerate:
    """ops/sp_common satellite: the SAME SP code path must run on a
    mesh whose sequence axis is size 1 (single-host slice) or absent —
    previously both wrappers required `jax.shard_map` (jax 0.6+) and a
    non-trivial axis."""

    def _qkv(self):
        import jax
        import jax.numpy as jnp
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 16, 8),
                              jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 16, 8),
                              jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 16, 8),
                              jnp.float32)
        return q, k, v

    @pytest.mark.parametrize('kind', ['ring', 'ulysses'])
    def test_sequence_axis_size_one(self, kind):
        import jax.numpy as jnp

        from skypilot_tpu.ops.attention import flash_attention
        from skypilot_tpu.ops.ring_attention import ring_attention
        from skypilot_tpu.ops.ulysses_attention import ulysses_attention
        from skypilot_tpu.parallel import mesh as mesh_lib
        import jax
        q, k, v = self._qkv()
        ref = flash_attention(q, k, v, causal=True)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(sequence=1, tensor=2),
            devices=jax.devices()[:2])
        fn = ring_attention if kind == 'ring' else ulysses_attention
        out = fn(q, k, v, mesh=mesh)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    @pytest.mark.parametrize('kind', ['ring', 'ulysses'])
    def test_mesh_without_sequence_axis(self, kind):
        import numpy as np

        import jax
        import jax.numpy as jnp

        from skypilot_tpu.ops.attention import flash_attention
        from skypilot_tpu.ops.ring_attention import ring_attention
        from skypilot_tpu.ops.ulysses_attention import ulysses_attention
        q, k, v = self._qkv()
        ref = flash_attention(q, k, v, causal=True)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]),
                                 ('tensor',))
        fn = ring_attention if kind == 'ring' else ulysses_attention
        out = fn(q, k, v, mesh=mesh)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    def test_real_split_still_exact(self):
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.ops.attention import flash_attention
        from skypilot_tpu.ops.ring_attention import ring_attention
        from skypilot_tpu.parallel import mesh as mesh_lib
        q, k, v = self._qkv()
        ref = flash_attention(q, k, v, causal=True)
        mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(sequence=4),
                                   devices=jax.devices()[:4])
        out = ring_attention(q, k, v, mesh=mesh)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    def test_sp_degree(self):
        import numpy as np

        import jax

        from skypilot_tpu.ops import sp_common
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(sequence=2),
                                   devices=jax.devices()[:2])
        assert sp_common.sp_degree(mesh, 'sequence') == 2
        bare = jax.sharding.Mesh(np.array(jax.devices()[:1]),
                                 ('tensor',))
        assert sp_common.sp_degree(bare, 'sequence') == 1
        assert sp_common.sp_degree(None, 'sequence') == 1


# --------------------------------------------------------- rank protocol


class TestCoordinator:

    def test_local_broadcast_and_stats(self):
        coord = coordinator_lib.SliceCoordinator(3)
        try:
            for _ in range(4):
                coord.tick()
            coord.broadcast(coordinator_lib.CMD_ADMIT, slot=1, tokens=9)
            stats = coord.stats()
            assert stats['num_hosts'] == 3
            assert stats['ranks_alive'] == 3
            assert stats['degraded'] is False
            assert stats['sync_count'] == 5
            assert stats['sync_ms_mean'] > 0
        finally:
            coord.close()

    def test_follower_exception_is_rank_death_as_a_unit(self):
        executed = []

        def boom(cmd):
            executed.append(cmd.kind)
            if len(executed) >= 3:
                raise RuntimeError('host OOM')

        coord = coordinator_lib.SliceCoordinator(
            2, channels=[coordinator_lib.LocalRank(1, executor=boom)])
        try:
            coord.tick()
            coord.tick()
            with pytest.raises(coordinator_lib.RankDead) as err:
                coord.tick()
            assert err.value.rank == 1
            assert coord.degraded and coord.dead_ranks == [1]
            # Every later command fails fast: a half-dead slice never
            # half-serves.
            with pytest.raises(coordinator_lib.RankDead):
                coord.tick()
        finally:
            coord.close()

    def test_ack_timeout_is_rank_death(self):
        def hang(cmd):
            del cmd
            time.sleep(5)

        coord = coordinator_lib.SliceCoordinator(
            2, channels=[coordinator_lib.LocalRank(1, executor=hang)],
            ack_timeout=0.2)
        try:
            with pytest.raises(coordinator_lib.RankDead,
                               match='timeout'):
                coord.tick()
        finally:
            coord.close()

    def test_tcp_follower_roundtrip(self):
        """The REAL-slice transport: commands out, acks back, shutdown
        ends the follower loop."""
        a, b = socket.socketpair()
        seen = []
        follower = threading.Thread(
            target=coordinator_lib.follower_serve,
            args=(b, 1, lambda cmd: seen.append((cmd.kind, cmd.seq))),
            daemon=True)
        follower.start()
        coord = coordinator_lib.SliceCoordinator(
            2, channels=[coordinator_lib.TcpRank(1, a)])
        coord.tick()
        coord.broadcast(coordinator_lib.CMD_PREFILL, tokens=128)
        assert coord.stats()['sync_count'] == 2
        coord.close()
        follower.join(timeout=5)
        assert not follower.is_alive()
        assert seen == [(coordinator_lib.CMD_TICK, 1),
                        (coordinator_lib.CMD_PREFILL, 2),
                        (coordinator_lib.CMD_SHUTDOWN, 3)]

    def test_tcp_disconnect_is_rank_death(self):
        a, b = socket.socketpair()
        coord = coordinator_lib.SliceCoordinator(
            2, channels=[coordinator_lib.TcpRank(1, a)],
            ack_timeout=5.0)
        b.close()   # the follower host vanished
        with pytest.raises(coordinator_lib.RankDead):
            coord.tick()
        coord.close()

    def test_command_json_roundtrip(self):
        cmd = coordinator_lib.Command(kind='admit', seq=7,
                                      payload={'slot': 2, 'tokens': 33})
        back = coordinator_lib.Command.from_json(cmd.to_json())
        assert (back.kind, back.seq, back.payload) == (
            'admit', 7, {'slot': 2, 'tokens': 33})


# ----------------------------------------------- sequence-parallel prefill


class TestPrefillSp:

    def test_matches_flash_prefill(self, tiny):
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import decode
        cfg, params = tiny
        prompt = jnp.asarray([list(range(1, 49))], jnp.int32)
        _, ref = decode.prefill(cfg, params, prompt, max_len=64)
        mesh = slice_replica.build_slice_mesh(2, cfg, sequence=2)
        sp_cache = jax.jit(lambda p, t: decode.prefill_sp(
            cfg, p, t, mesh=mesh, max_len=64))(params, prompt)
        assert int(sp_cache['index']) == 48
        for leaf in ('k', 'v'):
            got = jnp.asarray(sp_cache[leaf], jnp.float32)[..., :48, :]
            want = jnp.asarray(ref[leaf], jnp.float32)[..., :48, :]
            assert float(jnp.max(jnp.abs(got - want))) < 1e-4

    def test_moe_rejected(self, tiny):
        import dataclasses

        import jax.numpy as jnp

        from skypilot_tpu.models import decode
        cfg, params = tiny
        moe_cfg = dataclasses.replace(cfg, n_experts=4)
        mesh = slice_replica.build_slice_mesh(2, cfg, sequence=2)
        with pytest.raises(ValueError, match='MoE'):
            decode.prefill_sp(moe_cfg, params,
                              jnp.zeros((1, 8), jnp.int32),
                              mesh=mesh, max_len=64)


# ------------------------------------------------------- token exactness


class TestSliceEngineExactness:

    def test_two_host_token_exact(self, tiny):
        """2-host emulated sharded replica (default layout: tensor=2)
        vs the single-process engine — float KV pool, greedy AND
        sampled, with the SP one-shot prefill on the long prompts."""
        cfg, params = tiny
        ref = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=128, slots=2, prefill_chunk=16,
            kv_pages=48, page_size=8)
        try:
            want = _outputs(ref)
        finally:
            ref.stop()
        eng = slice_replica.SliceReplicaEngine(
            cfg, params, num_hosts=2, sp_threshold=32, max_len=128,
            slots=2, prefill_chunk=16, kv_pages=48, page_size=8)
        try:
            got = _outputs(eng)
            stats = eng.stats()
        finally:
            eng.stop()
        assert got == want
        assert stats['num_hosts'] == 2
        assert stats['slice']['tensor_degree'] == 2
        # The two long prompts went through the one-shot SP prefill
        # on first encounter; the sampled pass reuses their pages via
        # the prefix cache, and the short prompt stayed chunked.
        assert stats['slice']['sp_prefills'] == 2
        assert stats['slice']['sync_count'] > 0
        # The span records the coordinated-tick overhead.
        spans = stats['recent_spans']
        assert all('slice_sync_ms' in s for s in spans)

    def test_two_host_sequence_axis_int8_kv_token_exact(self, tiny):
        """sequence=2 layout (real ring split) + int8 KV pages: still
        token-exact vs the single-process int8 engine."""
        cfg, params = tiny
        ref = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=128, slots=2, prefill_chunk=16,
            kv_pages=48, page_size=8, quantize_kv=True)
        try:
            want = _outputs(ref)
        finally:
            ref.stop()
        eng = slice_replica.SliceReplicaEngine(
            cfg, params, num_hosts=2, sequence=2, sp_threshold=32,
            max_len=128, slots=2, prefill_chunk=16, kv_pages=48,
            page_size=8, quantize_kv=True)
        try:
            got = _outputs(eng)
            stats = eng.stats()
        finally:
            eng.stop()
        assert got == want
        assert stats['slice']['sp_degree'] == 2
        assert stats['slice']['sp_prefills'] == 2


# ------------------------------------------------------------ rank death


class TestRankDeath:

    def test_rank_death_fails_replica_as_a_unit(self, tiny):
        from skypilot_tpu.chaos import faults as faults_lib
        from skypilot_tpu.chaos import injector
        cfg, params = tiny
        plan = faults_lib.FaultPlan(
            seed=0, name='t',
            faults=[faults_lib.Fault(site='serve.rank_exec',
                                     effect='raise',
                                     where={'rank': 1}, nth=[6])])
        injector.arm(plan)
        eng = slice_replica.SliceReplicaEngine(
            cfg, params, num_hosts=2, sp_threshold=10_000,
            max_len=128, slots=2, prefill_chunk=16)
        try:
            with pytest.raises(RuntimeError, match='rank 1 died'):
                eng.generate(list(range(1, 30)), 20, timeout=60)
            stats = eng.stats()
            assert stats['failed'] is True
            assert stats['slice']['degraded'] is True
            assert stats['slice']['dead_ranks'] == [1]
            # Submits after the death fail fast, like any dead engine.
            with pytest.raises(RuntimeError):
                eng.submit([1, 2, 3], 4)
        finally:
            eng.stop()
            injector.disarm()


# ----------------------------------------------------- num_hosts plumbing


class TestNumHostsPlumbing:

    def test_role_pool_num_hosts_round_trip(self):
        from skypilot_tpu import exceptions
        from skypilot_tpu.serve.service_spec import SkyServiceSpec
        spec = SkyServiceSpec.from_yaml_config({
            'roles': {
                'decode': {'replicas': 2, 'num_hosts': 4},
                'prefill': {'replicas': 1},
            }})
        assert spec.role_specs['decode'].num_hosts == 4
        assert spec.role_specs['prefill'].num_hosts == 1
        back = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
        assert back.role_specs['decode'].num_hosts == 4
        with pytest.raises(exceptions.InvalidTaskError,
                           match='num_hosts'):
            SkyServiceSpec(roles={'decode': {'replicas': 1,
                                             'num_hosts': 0}})

    def test_serve_state_num_hosts_column_and_migration(
            self, monkeypatch, tmp_path):
        """Old DBs (no num_hosts / no role column) load cleanly; new
        rows persist the slice width."""
        from skypilot_tpu.serve import serve_state
        db = tmp_path / 'serve.db'
        monkeypatch.setenv('SKYTPU_SERVE_DB', str(db))
        # Build a PRE-slice (and pre-role) schema by hand.
        conn = sqlite3.connect(str(db))
        conn.execute("""CREATE TABLE replicas (
            service_name TEXT, replica_id INTEGER, cluster_name TEXT,
            status TEXT, url TEXT, is_spot INTEGER DEFAULT 0,
            version INTEGER DEFAULT 1, launched_at REAL,
            PRIMARY KEY (service_name, replica_id))""")
        conn.execute(
            'INSERT INTO replicas (service_name, replica_id, '
            "cluster_name, status) VALUES ('svc', 1, 'svc-1', 'READY')")
        conn.commit()
        conn.close()
        rows = serve_state.get_replicas('svc')
        assert rows[0]['num_hosts'] == 1      # migrated default
        assert rows[0]['role'] == 'mixed'
        rid = serve_state.allocate_replica('svc', 'svc', role='decode',
                                           num_hosts=4)
        row = [r for r in serve_state.get_replicas('svc')
               if r['replica_id'] == rid][0]
        assert row['num_hosts'] == 4

    def test_scale_up_threads_num_hosts_env(self, monkeypatch):
        """scale_up(num_hosts=N) lands SKYTPU_SERVE_REPLICA_NUM_HOSTS
        in the replica env and widens the replica cluster to N nodes."""
        import skypilot_tpu as sky
        from skypilot_tpu.serve import replica_managers
        from skypilot_tpu.serve import serve_state
        from skypilot_tpu.serve import service_spec

        captured = {}

        def fake_launch(task, **kwargs):
            captured['envs'] = dict(task.envs)
            captured['num_nodes'] = task.num_nodes
            raise sky.exceptions.SkyTpuError('stop here')

        monkeypatch.setattr('skypilot_tpu.execution.launch',
                            fake_launch)
        spec = service_spec.SkyServiceSpec()
        task = sky.Task(name='t', run='true')
        task.set_resources(sky.Resources(cloud='local'))
        serve_state.add_service('svc-nh', spec_json={},
                                task_yaml_path='')
        manager = replica_managers.ReplicaManager('svc-nh', spec, task)
        rid = manager.scale_up(role='decode', num_hosts=2)
        deadline = time.time() + 10
        while 'envs' not in captured and time.time() < deadline:
            time.sleep(0.05)
        assert captured['envs'][
            replica_managers.ENV_REPLICA_NUM_HOSTS] == '2'
        assert captured['envs'][
            replica_managers.ENV_REPLICA_ROLE] == 'decode'
        assert captured['num_nodes'] == 2
        row = serve_state.get_replicas('svc-nh')[0]
        assert row['replica_id'] == rid and row['num_hosts'] == 2


# ----------------------------------------------------- through the real LB


def _serve_and_compare(tiny, num_hosts, **slice_kwargs):
    """One slice-replica model server + one single-process reference
    behind the REAL LB: tokens through the LB must match the reference
    exactly."""
    import requests

    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import model_server as model_server_lib
    from skypilot_tpu.serve import router as router_lib
    del tiny
    slice_server = model_server_lib.ModelServer(
        'tiny', max_len=64, max_batch=2, continuous_batching=True,
        prefill_chunk=16, kv_pages=48, page_size=8,
        num_hosts=num_hosts, **slice_kwargs)
    reference = model_server_lib.ModelServer(
        'tiny', max_len=64, max_batch=2, continuous_batching=True,
        prefill_chunk=16, kv_pages=48, page_size=8)
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1',
        router=router_lib.Router(threshold=10_000))
    stop = None
    try:
        port, stop = model_server_lib.start_background(slice_server)
        lb.set_replicas([{'url': f'http://127.0.0.1:{port}',
                          'role': 'mixed'}])
        lb_port = lb.start()
        for prompt in ([1, 2, 3, 4, 5], list(range(1, 45))):
            resp = requests.post(
                f'http://127.0.0.1:{lb_port}/generate',
                json={'prompt_ids': [prompt], 'max_new_tokens': 6},
                timeout=120)
            assert resp.status_code == 200
            assert resp.json()['tokens'] == reference.generate(
                [prompt], 6)
        health = requests.get(f'http://127.0.0.1:{port}/', timeout=10)
        payload = health.json()
        assert payload['num_hosts'] == num_hosts
        assert payload['slice']['ranks_alive'] == num_hosts
    finally:
        lb.stop()
        if stop is not None:
            stop()
        slice_server.close()
        reference.close()


def test_two_host_through_lb_token_exact(tiny):
    _serve_and_compare(tiny, num_hosts=2, sp_threshold=24)


def test_four_host_through_lb_token_exact(tiny):
    # 4 hosts factor as sequence=2 x tensor=2 for tiny.
    _serve_and_compare(tiny, num_hosts=4, sp_threshold=24)


# -------------------------------------------------- follower executors


class TestFollowerExecutor:
    """Real-slice followers execute the command log against their own
    devices: replaying rank 0's broadcasts through a FollowerExecutor
    must mirror the engine's device state — the gang contract a real
    multi-host slice rests on."""

    GEOM = dict(max_len=64, slots=2, prefill_chunk=8, kv_pages=48,
                page_size=8)

    def _run(self, tiny, spec_tokens):
        import numpy as np
        cfg, params = tiny
        follower = slice_replica.FollowerExecutor(
            cfg, params, spec_tokens=spec_tokens, **self.GEOM)
        chan = coordinator_lib.LocalRank(1, follower)
        eng = slice_replica.SliceReplicaEngine(
            cfg, params, num_hosts=2, rank_channels=[chan],
            spec_tokens=spec_tokens, **self.GEOM)
        try:
            outs = [eng.generate(p, n, timeout=300)
                    for p, n in (([3, 1, 4, 1, 5, 9, 2, 6], 8),
                                 ([7], 4), (list(range(1, 25)), 6))]
            # Broadcasts ack synchronously, so the follower has fully
            # executed the log: its sampler state and block tables
            # must equal rank 0's BIT-FOR-BIT (same jitted ops, same
            # order), and the KV pool must match to float rounding
            # (rank 0 computes under the slice mesh, the follower
            # unsharded).
            for k in eng._state:
                assert np.array_equal(np.asarray(eng._state[k]),
                                      np.asarray(follower._state[k])), k
            for k in ('block_tables', 'lengths'):
                assert np.array_equal(
                    np.asarray(eng._cache[k]),
                    np.asarray(follower._cache[k])), k
            a, b = eng._cache['k'], follower._cache['k']
            diff = np.abs(np.asarray(a, np.float32) -
                          np.asarray(b, np.float32)).max()
            assert diff < 1e-3, diff
            assert follower._commands > 0
        finally:
            eng.stop()
        return outs

    def test_follower_mirrors_engine_state(self, tiny):
        self._run(tiny, spec_tokens=0)

    def test_follower_mirrors_spec_ticks(self, tiny):
        """Draft batches ride the TICK broadcast: a spec engine's
        follower dispatches the identical verify steps and lands in
        the identical state — and outputs stay byte-identical to the
        non-spec slice."""
        assert self._run(tiny, spec_tokens=0) == \
            self._run(tiny, spec_tokens=3)

    def test_follower_release_parks_tables(self, tiny):
        import numpy as np
        cfg, params = tiny
        follower = slice_replica.FollowerExecutor(cfg, params,
                                                  **self.GEOM)
        chan = coordinator_lib.LocalRank(1, follower)
        eng = slice_replica.SliceReplicaEngine(
            cfg, params, num_hosts=2, rank_channels=[chan],
            **self.GEOM)
        try:
            eng.generate([3, 1, 4, 1, 5], 4, timeout=300)
            # The finished slot's RELEASE was broadcast: the
            # follower's table row is parked on the null page.
            tables = np.asarray(follower._cache['block_tables'])
            assert (tables == 0).all()
        finally:
            eng.stop()
