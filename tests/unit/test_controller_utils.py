"""File-mount translation for controller handoff (VERDICT r2 missing #1).

Parity target: reference controller_utils.py:679
`maybe_translate_local_file_mounts_and_sync_up`.  Hermetic via the
LOCAL store type (directory-backed bucket) + local provisioner.
"""
from __future__ import annotations

import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import config as config_lib
from skypilot_tpu import global_user_state
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import controller_utils
from skypilot_tpu.utils import dag_utils


@pytest.fixture(autouse=True)
def _local_bucket_config(_isolated_home):
    config_lib.set_nested(('jobs', 'bucket'), 'local://auto')
    global_user_state.set_enabled_clouds(['local'])
    yield


def _make_tree(root, files):
    for rel, content in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)


class TestTranslate:

    def test_noop_without_local_paths(self):
        task = sky.Task(name='t', run='true',
                        file_mounts={'/data': 'gs://bucket/path'})
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task)
        assert task.file_mounts == {'/data': 'gs://bucket/path'}
        assert not task.storage_mounts

    def test_workdir_becomes_bucket_mount(self, tmp_path):
        wd = tmp_path / 'proj'
        _make_tree(wd, {'train.py': 'print(1)', 'pkg/util.py': 'x=2'})
        task = sky.Task(name='t', run='true', workdir=str(wd))
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task)
        assert task.workdir is None
        mount = task.storage_mounts['~/sky_workdir']
        assert mount.mode is storage_lib.StorageMode.COPY
        store = mount.get_default_store()
        assert store.store_type is storage_lib.StoreType.LOCAL
        # Uploaded content is in the bucket dir.
        assert os.path.exists(os.path.join(store._data_dir, 'train.py'))
        assert os.path.exists(
            os.path.join(store._data_dir, 'pkg', 'util.py'))

    def test_file_and_dir_mounts(self, tmp_path):
        data = tmp_path / 'data'
        _make_tree(data, {'a.txt': 'A'})
        cfg = tmp_path / 'config.yaml'
        cfg.write_text('k: v')
        task = sky.Task(name='t', run='true', file_mounts={
            '/mnt/data': str(data),
            '/etc/app/settings.yaml': str(cfg),
            '/remote': 'gs://keepme',
        })
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task)
        # Cloud URL mounts pass through untouched.
        assert task.file_mounts == {'/remote': 'gs://keepme'}
        # Dir mount at its dst; single file staged under dst basename in
        # the parent-dir mount.
        assert '/mnt/data' in task.storage_mounts
        parent_mount = task.storage_mounts['/etc/app']
        store = parent_mount.get_default_store()
        assert os.path.exists(
            os.path.join(store._data_dir, 'settings.yaml'))

    def test_file_into_translated_dir_mount_merges(self, tmp_path):
        """{'/data': dir, '/data/cfg.yaml': file} must not clobber the
        dir mount (code-review finding): the file joins its bucket."""
        data = tmp_path / 'data'
        _make_tree(data, {'a.txt': 'A'})
        cfg = tmp_path / 'conf.yaml'
        cfg.write_text('k: v')
        task = sky.Task(name='t', run='true', file_mounts={
            '/data': str(data),
            '/data/cfg.yaml': str(cfg),
        })
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task)
        assert set(task.storage_mounts) == {'/data'}
        store = task.storage_mounts['/data'].get_default_store()
        assert os.path.exists(os.path.join(store._data_dir, 'a.txt'))
        assert os.path.exists(os.path.join(store._data_dir, 'cfg.yaml'))

    def test_yaml_round_trip_preserves_prefix(self, tmp_path):
        wd = tmp_path / 'proj'
        _make_tree(wd, {'main.py': 'pass'})
        task = sky.Task(name='t', run='true', workdir=str(wd))
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task)
        cfg = task.to_yaml_config()
        task2 = sky.Task.from_yaml_config(cfg)
        mount = task2.storage_mounts['~/sky_workdir']
        store = mount.get_default_store()
        # The re-created store targets the same prefix dir (not the
        # bucket root).
        orig = task.storage_mounts['~/sky_workdir'].get_default_store()
        assert store._data_dir == orig._data_dir
        assert store.store_type is storage_lib.StoreType.LOCAL


class TestClusterModeE2E:
    """Cluster-mode managed job with local file mounts runs hermetically
    (the verdict's done-criterion for missing #1)."""

    def test_job_reads_translated_mounts(self, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYTPU_JOB_STATUS_CHECK_GAP', '0.3')
        monkeypatch.setenv('SKYTPU_JOB_STARTED_CHECK_GAP', '0.3')
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import core as jobs_core
        from skypilot_tpu.jobs import state

        wd = tmp_path / 'proj'
        _make_tree(wd, {'hello.txt': 'FROM_WORKDIR'})
        data = tmp_path / 'data'
        _make_tree(data, {'d.txt': 'FROM_DATA'})
        out_path = tmp_path / 'result.txt'

        task = sky.Task(
            name='translated', workdir=str(wd),
            file_mounts={'/tmp/skytpu_test_mounts/data': str(data)},
            run=('cat ~/sky_workdir/hello.txt '
                 f'/tmp/skytpu_test_mounts/data/d.txt > {out_path}'))
        task.set_resources(sky.Resources(cloud='local'))

        # Same translation jobs.launch does in cluster mode, then drive
        # the controller inline against the round-tripped YAML (what the
        # controller cluster would load).
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task, task_type='jobs')
        dag = dag_utils.convert_entrypoint_to_dag(task)
        job_id = state.allocate_job_id('translated')
        yaml_path = os.path.join(
            jobs_core._dag_yaml_dir(),  # pylint: disable=protected-access
            f'translated-{job_id}.yaml')
        dag_utils.dump_chain_dag_to_yaml(dag, yaml_path)
        state.submit_job(job_id, 'translated', yaml_path, ['translated'])
        state.set_status(job_id, 0, state.ManagedJobStatus.SUBMITTED)
        controller_lib.JobsController(job_id, yaml_path).run()

        assert (state.get_status(job_id) is
                state.ManagedJobStatus.SUCCEEDED)
        assert out_path.read_text() == 'FROM_WORKDIRFROM_DATA'
