"""Tokenizer backends + streaming decode.

HFTokenizer is pinned against the `tokenizers` library directly (build
a real BPE tokenizer.json in-test).  SentencePieceTokenizer is pinned
against a hand-serialized ModelProto (the pure-Python parser reads the
same wire format sentencepiece writes).  StreamDecoder is checked for
UTF-8 split safety.
"""
from __future__ import annotations

import json
import struct

import pytest

from skypilot_tpu.models import tokenizer as tok_lib


# ------------------------------------------------------------------ HF BPE


def _build_bpe_json(tmp_path):
    """A tiny real byte-level BPE tokenizer via the tokenizers lib."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models, pre_tokenizers, decoders, trainers
    tk = tokenizers.Tokenizer(models.BPE(unk_token=None))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tk.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=['<|begin|>', '<|end|>'],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tk.train_from_iterator(
        ['the quick brown fox jumps over the lazy dog',
         'hello world, hello tpu serving'] * 50, trainer)
    path = tmp_path / 'tokenizer.json'
    tk.save(str(path))
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps({
        'bos_token': '<|begin|>',
        'eos_token': {'content': '<|end|>'},
    }))
    return tmp_path


def test_hf_tokenizer_round_trip(tmp_path):
    d = _build_bpe_json(tmp_path)
    tok = tok_lib.load_tokenizer(str(d))
    assert isinstance(tok, tok_lib.HFTokenizer)
    text = 'hello world, the quick fox'
    ids = tok.encode(text)
    assert ids and all(isinstance(i, int) for i in ids)
    assert tok.decode(ids) == text
    assert tok.eos_id is not None and tok.bos_id is not None
    assert tok.encode(text, add_bos=True)[0] == tok.bos_id
    assert tok.vocab_size > 250


def test_stream_decoder_utf8_safe(tmp_path):
    """Multi-byte chars split across byte-level BPE tokens must never
    emit partial UTF-8 (no replacement chars mid-stream)."""
    d = _build_bpe_json(tmp_path)
    tok = tok_lib.load_tokenizer(str(d))
    text = 'héllo wörld ünïcode 東京 🚀 done'
    ids = tok.encode(text)
    dec = tok_lib.StreamDecoder(tok)
    out = []
    for i in ids:
        delta = dec.push(i)
        assert '�' not in delta
        out.append(delta)
    out.append(dec.finish())
    assert ''.join(out) == text


def test_byte_tokenizer_round_trip():
    tok = tok_lib.ByteTokenizer()
    assert tok.decode(tok.encode('hi 東京')) == 'hi 東京'
    assert tok.eos_id == 0
    dec = tok_lib.StreamDecoder(tok)
    deltas = [dec.push(t) for t in tok.encode('a東b')]
    assert '�' not in ''.join(deltas)
    assert ''.join(deltas) + dec.finish() == 'a東b'


# ------------------------------------------------------- SentencePiece

def _varint(n: int) -> bytes:
    out = b''
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _sp_piece(text: str, score: float, ptype: int = 1) -> bytes:
    body = (bytes([0x0A]) + _varint(len(text.encode())) + text.encode() +
            bytes([0x15]) + struct.pack('<f', score))
    if ptype != 1:
        body += bytes([0x18]) + _varint(ptype)
    return bytes([0x0A]) + _varint(len(body)) + body


def _build_sp_model(tmp_path, model_type: int = 1):
    """Serialize a ModelProto by hand: <unk>, <s>, </s>, some word
    pieces, and the 256 byte-fallback pieces."""
    pieces = [_sp_piece('<unk>', 0.0, 2), _sp_piece('<s>', 0.0, 3),
              _sp_piece('</s>', 0.0, 3)]
    vocab = ['▁hello', '▁world', '▁the', '▁quick', 'ing', '▁fox',
             'hel', 'lo', '▁', 'h', 'e', 'l', 'o', 'w', 'r', 'd',
             't', 'q', 'u', 'i', 'c', 'k', 'n', 'g', 'f', 'x']
    for rank, piece in enumerate(vocab):
        # Longer pieces score better, like a trained unigram model.
        pieces.append(_sp_piece(piece, -float(rank) / 4.0 - 1.0))
    for b in range(256):
        pieces.append(_sp_piece(f'<0x{b:02X}>', -100.0, 6))
    trainer = bytes([0x18]) + _varint(model_type)  # field 3 varint
    blob = (b''.join(pieces) +
            bytes([0x12]) + _varint(len(trainer)) + trainer)
    path = tmp_path / 'tokenizer.model'
    path.write_bytes(blob)
    return str(tmp_path)


def test_sentencepiece_parse_and_round_trip(tmp_path):
    d = _build_sp_model(tmp_path)
    tok = tok_lib.load_tokenizer(d)
    assert isinstance(tok, tok_lib.SentencePieceTokenizer)
    assert tok.bos_id == 1 and tok.eos_id == 2
    ids = tok.encode('hello world')
    # Viterbi must pick the big pieces, not char soup.
    assert ids == [tok._id_of['▁hello'], tok._id_of['▁world']]
    assert tok.decode(ids) == 'hello world'
    assert tok.encode('hello', add_bos=True)[0] == 1


def test_sentencepiece_byte_fallback(tmp_path):
    d = _build_sp_model(tmp_path)
    tok = tok_lib.load_tokenizer(d)
    # 東 is not in the vocab: must byte-fallback, and decode restores it.
    ids = tok.encode('hello 東')
    assert tok.decode(ids) == 'hello 東'
    byte_ids = [i for i in ids
                if tok._pieces[i][2] == tok_lib._SP_BYTE]
    assert len(byte_ids) == 3  # 東 is 3 UTF-8 bytes


def test_load_tokenizer_fallbacks(tmp_path):
    assert isinstance(tok_lib.load_tokenizer(None),
                      tok_lib.ByteTokenizer)
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert isinstance(tok_lib.load_tokenizer(str(empty)),
                      tok_lib.ByteTokenizer)
