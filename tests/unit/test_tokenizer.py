"""Tokenizer backends + streaming decode.

HFTokenizer is pinned against the `tokenizers` library directly (build
a real BPE tokenizer.json in-test).  SentencePieceTokenizer is pinned
against a hand-serialized ModelProto (the pure-Python parser reads the
same wire format sentencepiece writes).  StreamDecoder is checked for
UTF-8 split safety.
"""
from __future__ import annotations

import json
import struct

import pytest

from skypilot_tpu.models import tokenizer as tok_lib


# ------------------------------------------------------------------ HF BPE


def _build_bpe_json(tmp_path):
    """A tiny real byte-level BPE tokenizer via the tokenizers lib."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models, pre_tokenizers, decoders, trainers
    tk = tokenizers.Tokenizer(models.BPE(unk_token=None))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tk.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=['<|begin|>', '<|end|>'],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tk.train_from_iterator(
        ['the quick brown fox jumps over the lazy dog',
         'hello world, hello tpu serving'] * 50, trainer)
    path = tmp_path / 'tokenizer.json'
    tk.save(str(path))
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps({
        'bos_token': '<|begin|>',
        'eos_token': {'content': '<|end|>'},
    }))
    return tmp_path


def test_hf_tokenizer_round_trip(tmp_path):
    d = _build_bpe_json(tmp_path)
    tok = tok_lib.load_tokenizer(str(d))
    assert isinstance(tok, tok_lib.HFTokenizer)
    text = 'hello world, the quick fox'
    ids = tok.encode(text)
    assert ids and all(isinstance(i, int) for i in ids)
    assert tok.decode(ids) == text
    assert tok.eos_id is not None and tok.bos_id is not None
    assert tok.encode(text, add_bos=True)[0] == tok.bos_id
    assert tok.vocab_size > 250


def test_stream_decoder_utf8_safe(tmp_path):
    """Multi-byte chars split across byte-level BPE tokens must never
    emit partial UTF-8 (no replacement chars mid-stream)."""
    d = _build_bpe_json(tmp_path)
    tok = tok_lib.load_tokenizer(str(d))
    text = 'héllo wörld ünïcode 東京 🚀 done'
    ids = tok.encode(text)
    dec = tok_lib.StreamDecoder(tok)
    out = []
    for i in ids:
        delta = dec.push(i)
        assert '�' not in delta
        out.append(delta)
    out.append(dec.finish())
    assert ''.join(out) == text


def test_byte_tokenizer_round_trip():
    tok = tok_lib.ByteTokenizer()
    assert tok.decode(tok.encode('hi 東京')) == 'hi 東京'
    assert tok.eos_id == 0
    dec = tok_lib.StreamDecoder(tok)
    deltas = [dec.push(t) for t in tok.encode('a東b')]
    assert '�' not in ''.join(deltas)
    assert ''.join(deltas) + dec.finish() == 'a東b'


# ------------------------------------------------------- SentencePiece

def _varint(n: int) -> bytes:
    out = b''
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _sp_piece(text: str, score: float, ptype: int = 1) -> bytes:
    body = (bytes([0x0A]) + _varint(len(text.encode())) + text.encode() +
            bytes([0x15]) + struct.pack('<f', score))
    if ptype != 1:
        body += bytes([0x18]) + _varint(ptype)
    return bytes([0x0A]) + _varint(len(body)) + body


def _build_sp_model(tmp_path, model_type: int = 1):
    """Serialize a ModelProto by hand: <unk>, <s>, </s>, some word
    pieces, and the 256 byte-fallback pieces."""
    pieces = [_sp_piece('<unk>', 0.0, 2), _sp_piece('<s>', 0.0, 3),
              _sp_piece('</s>', 0.0, 3)]
    vocab = ['▁hello', '▁world', '▁the', '▁quick', 'ing', '▁fox',
             'hel', 'lo', '▁', 'h', 'e', 'l', 'o', 'w', 'r', 'd',
             't', 'q', 'u', 'i', 'c', 'k', 'n', 'g', 'f', 'x']
    for rank, piece in enumerate(vocab):
        # Longer pieces score better, like a trained unigram model.
        pieces.append(_sp_piece(piece, -float(rank) / 4.0 - 1.0))
    for b in range(256):
        pieces.append(_sp_piece(f'<0x{b:02X}>', -100.0, 6))
    trainer = bytes([0x18]) + _varint(model_type)  # field 3 varint
    blob = (b''.join(pieces) +
            bytes([0x12]) + _varint(len(trainer)) + trainer)
    path = tmp_path / 'tokenizer.model'
    path.write_bytes(blob)
    return str(tmp_path)


def test_sentencepiece_parse_and_round_trip(tmp_path):
    d = _build_sp_model(tmp_path)
    tok = tok_lib.load_tokenizer(d)
    assert isinstance(tok, tok_lib.SentencePieceTokenizer)
    assert tok.bos_id == 1 and tok.eos_id == 2
    ids = tok.encode('hello world')
    # Viterbi must pick the big pieces, not char soup.
    assert ids == [tok._id_of['▁hello'], tok._id_of['▁world']]
    assert tok.decode(ids) == 'hello world'
    assert tok.encode('hello', add_bos=True)[0] == 1


def test_sentencepiece_byte_fallback(tmp_path):
    d = _build_sp_model(tmp_path)
    tok = tok_lib.load_tokenizer(d)
    # 東 is not in the vocab: must byte-fallback, and decode restores it.
    ids = tok.encode('hello 東')
    assert tok.decode(ids) == 'hello 東'
    byte_ids = [i for i in ids
                if tok._pieces[i][2] == tok_lib._SP_BYTE]
    assert len(byte_ids) == 3  # 東 is 3 UTF-8 bytes


def test_sentencepiece_bpe_merge_order(tmp_path):
    """model_type=2 must use merge-rank BPE, not unigram Viterbi: with
    these scores the BPE merge order yields ('hel','lo') for 'hello'
    (the 'hel' merge outranks everything containing '▁hello'), while
    Viterbi would pick the single best-scoring full piece."""
    d = _build_sp_model(tmp_path, model_type=2)
    tok = tok_lib.load_tokenizer(d)
    assert tok._model_type == 2
    ids = tok.encode('hello world')
    assert all(i >= 0 for i in ids)
    assert tok.decode(ids) == 'hello world'


# ------------------- parity vs the tokenizers lib (independent impls)


def _sp_model_from_vocab(tmp_path, vocab, model_type):
    """Serialize a ModelProto whose piece table is exactly `vocab`
    ([(text, score)]) plus the standard specials + byte pieces."""
    pieces = [_sp_piece('<unk>', 0.0, 2), _sp_piece('<s>', 0.0, 3),
              _sp_piece('</s>', 0.0, 3)]
    for text, score in vocab:
        pieces.append(_sp_piece(text, score))
    for b in range(256):
        pieces.append(_sp_piece(f'<0x{b:02X}>', -100.0, 6))
    trainer = bytes([0x18]) + _varint(model_type)
    blob = (b''.join(pieces) +
            bytes([0x12]) + _varint(len(trainer)) + trainer)
    path = tmp_path / 'tokenizer.model'
    path.write_bytes(blob)
    return str(path)


_PARITY_TEXTS = ['hello world', 'the quick fox', 'low lower lowest',
                 'hellohello', 'quick quick quick', 'world worlds']


def test_unigram_viterbi_parity_vs_tokenizers_lib(tmp_path):
    """Our Viterbi segmentation against tokenizers.models.Unigram — a
    real, independent unigram implementation (sentencepiece itself is
    not in the image; VERDICT r4 weak #6 asked for a non-self-
    referential pin).  Same pieces, same scores, same input string
    (pre-normalized so neither side's pre-tokenizer is in play)."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models
    chars = list('▁helowrdtquickfxns')
    words = ['▁hello', '▁world', '▁the', '▁quick', '▁fox', 'hel',
             'lo', 'low', 'lower', 'est', 'ick', 'wor', 'ld']
    vocab = ([('<unk>', 0.0)] +
             [(w, -1.0 - 0.37 * i) for i, w in enumerate(words)] +
             [(c, -8.0 - 0.11 * i) for i, c in enumerate(chars)])
    hf = tokenizers.Tokenizer(models.Unigram(vocab, unk_id=0))
    ours = tok_lib.SentencePieceTokenizer(
        _sp_model_from_vocab(tmp_path, vocab[1:], model_type=1))
    for text in _PARITY_TEXTS:
        normalized = '▁' + text.replace(' ', '▁')
        hf_tokens = hf.encode(normalized).tokens
        our_tokens = [ours._pieces[i][0] for i in ours.encode(text)]
        assert our_tokens == hf_tokens, (text, our_tokens, hf_tokens)


def test_bpe_merge_parity_vs_tokenizers_lib(tmp_path):
    """Our merge-rank BPE against tokenizers.models.BPE: the merge
    list ordered by rank maps to SP-BPE scores (-rank), so both sides
    must produce identical segmentations."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models
    chars = list('▁helowrdtquickfxs')
    merges = [('h', 'e'), ('l', 'o'), ('he', 'l'), ('hel', 'lo'),
              ('▁', 'hello'), ('w', 'o'), ('wo', 'r'), ('wor', 'ld'),
              ('l', 'd'), ('▁', 'world'), ('q', 'u'), ('i', 'c'),
              ('ic', 'k'), ('qu', 'ick'), ('▁', 'quick'),
              ('t', 'he'), ('▁', 'the')]
    # HF BPE wants vocab ids + ranked merges; SP-BPE encodes the same
    # ranks as descending scores on the merged pieces.
    hf_vocab, sp_vocab = {}, []
    for i, c in enumerate(chars):
        hf_vocab[c] = len(hf_vocab)
        sp_vocab.append((c, -200.0 - i))  # chars never drive merges
    for rank, (a, b) in enumerate(merges):
        piece = a + b
        if piece not in hf_vocab:
            hf_vocab[piece] = len(hf_vocab)
            sp_vocab.append((piece, -1.0 - rank))
    hf = tokenizers.Tokenizer(models.BPE(
        hf_vocab, [(a, b) for a, b in merges]))
    ours = tok_lib.SentencePieceTokenizer(
        _sp_model_from_vocab(tmp_path, sp_vocab, model_type=2))
    for text in _PARITY_TEXTS:
        normalized = '▁' + text.replace(' ', '▁')
        hf_tokens = hf.encode(normalized).tokens
        our_tokens = [ours._pieces[i][0] for i in ours.encode(text)]
        assert our_tokens == hf_tokens, (text, our_tokens, hf_tokens)


def test_bpe_diverges_from_viterbi_where_it_should(tmp_path):
    """A case where merge-order BPE and unigram Viterbi provably
    disagree — 'abc' with merges [(a,b),(b,c)] BPE-segments as
    [ab, c] (rank order), while these scores make Viterbi prefer
    [a, bc] — so this test discriminates the two algorithms: the old
    Viterbi-for-everything behavior fails it (ADVICE r4: BPE .model
    files silently got unigram segmentation)."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models
    sp_vocab = [('▁', -0.5), ('a', -1.0), ('b', -60.0), ('c', -70.0),
                ('ab', -1.0), ('bc', -2.0)]
    hf_vocab = {t: i for i, (t, _) in enumerate(sp_vocab)}
    hf = tokenizers.Tokenizer(models.BPE(
        hf_vocab, [('a', 'b'), ('b', 'c')]))
    path = _sp_model_from_vocab(tmp_path, sp_vocab, model_type=2)
    ours = tok_lib.SentencePieceTokenizer(path)
    our_tokens = [ours._pieces[i][0] for i in ours.encode('abc')]
    assert our_tokens == hf.encode('▁abc').tokens == ['▁', 'ab', 'c']
    # Sanity: the unigram path on the SAME pieces segments differently,
    # proving the parity above cannot pass by accident.
    ours._model_type = 1
    viterbi_tokens = [ours._pieces[i][0] for i in ours.encode('abc')]
    assert viterbi_tokens == ['▁', 'a', 'bc']


def test_hf_eos_fallback_from_vocab(tmp_path):
    """tokenizer.json without tokenizer_config.json: eos_id must fall
    back to a conventional EOS name in the vocab (ADVICE r4: stop_token
    None silently pinned every request at max_new_tokens)."""
    _build_bpe_json(tmp_path)
    (tmp_path / 'tokenizer_config.json').unlink()
    tok = tok_lib.load_tokenizer(str(tmp_path))
    assert isinstance(tok, tok_lib.HFTokenizer)
    assert tok.eos_id is not None
    assert tok.eos_token == '<|end|>'


def _build_instruct_bpe_json(tmp_path):
    """BPE vocab carrying chat turn-end markers (Llama-3-Instruct /
    ChatML style) alongside the base-model EOS names."""
    tokenizers = pytest.importorskip('tokenizers')
    from tokenizers import models, pre_tokenizers, decoders, trainers
    tk = tokenizers.Tokenizer(models.BPE(unk_token=None))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tk.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        special_tokens=['<|begin_of_text|>', '<|end_of_text|>',
                        '<|eot_id|>', '<|im_end|>'],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tk.train_from_iterator(['hello world'] * 50, trainer)
    tk.save(str(tmp_path / 'tokenizer.json'))
    return tmp_path


def test_hf_eos_fallback_multi_stop_set(tmp_path, monkeypatch):
    """Instruct checkpoint without tokenizer_config.json: the fallback
    picks the model-level EOS, but chat turn-end markers present in the
    vocab must join eos_ids (the serve stop set) and be surfaced in the
    warning — otherwise Llama-3-Instruct streams past every turn end
    to max_new_tokens (ADVICE round 5)."""
    warnings = []
    monkeypatch.setattr(tok_lib.logger, 'warning',
                        lambda msg, *a: warnings.append(str(msg)))
    _build_instruct_bpe_json(tmp_path)
    tok = tok_lib.load_tokenizer(str(tmp_path))
    assert tok.eos_token == '<|end_of_text|>'
    eot = tok._tok.token_to_id('<|eot_id|>')
    im_end = tok._tok.token_to_id('<|im_end|>')
    assert tok.eos_ids == {tok.eos_id, eot, im_end}
    warning = ' '.join(warnings)
    assert '<|eot_id|>' in warning and '<|im_end|>' in warning


def test_hf_config_eos_still_gains_chat_markers(tmp_path):
    """Even WITH tokenizer_config.json, chat markers in the vocab join
    the stop set (a base model never emits them — always safe)."""
    _build_instruct_bpe_json(tmp_path)
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps({
        'eos_token': '<|end_of_text|>'}))
    tok = tok_lib.load_tokenizer(str(tmp_path))
    assert tok.eos_id == tok._tok.token_to_id('<|end_of_text|>')
    assert tok._tok.token_to_id('<|eot_id|>') in tok.eos_ids
    assert len(tok.eos_ids) == 3


def test_eos_ids_base_interface():
    tok = tok_lib.ByteTokenizer()
    assert tok.eos_ids == {0}


def test_sp_control_tokens_not_encodable(tmp_path):
    """User text spelling a control token must NOT encode to its
    special id (EOS injection): real sentencepiece excludes
    CONTROL/UNKNOWN pieces from segmentation."""
    d = _build_sp_model(tmp_path)
    tok = tok_lib.load_tokenizer(d)
    assert tok.eos_id == 2
    ids = tok.encode('</s>')
    assert tok.eos_id not in ids and tok.bos_id not in ids
    # Spelled out from chars/bytes instead; decode survives.
    assert '</s>' in tok.decode(ids) or 's' in tok.decode(ids)


def test_load_tokenizer_fallbacks(tmp_path):
    assert isinstance(tok_lib.load_tokenizer(None),
                      tok_lib.ByteTokenizer)
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert isinstance(tok_lib.load_tokenizer(str(empty)),
                      tok_lib.ByteTokenizer)
