"""Observability layer: metrics core, Prometheus exposition, request
tracing, and the serving/training wiring (tier-1, CPU-only).

Covers the ISSUE-3 acceptance surface: label cardinality, histogram
bucket boundaries, concurrent increments from threads, a round-trip
test parsing the /metrics exposition of a LIVE model_server, and a
request submitted with X-SkyTPU-Request-Id yielding a span record
(queue/prefill/TTFT/decode) retrievable via stats() and visible in the
Chrome-trace timeline file.
"""
from __future__ import annotations

import json
import threading

import pytest
import requests

from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.utils import timeline


# ------------------------------------------------------------- metrics core


class TestCounterGauge:

    def test_counter_inc_and_expose(self):
        reg = metrics_lib.Registry()
        c = reg.counter('t_requests_total', 'Requests.')
        c.inc()
        c.inc(4)
        assert c.value == 5
        text = reg.expose()
        assert '# TYPE t_requests_total counter' in text
        assert 't_requests_total 5' in text

    def test_counter_rejects_negative(self):
        reg = metrics_lib.Registry()
        c = reg.counter('t_neg_total', 'x')
        with pytest.raises(ValueError, match='only go up'):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        reg = metrics_lib.Registry()
        g = reg.gauge('t_depth', 'x')
        g.set(7)
        g.inc(2)
        g.dec()
        assert g.value == 8

    def test_labels_make_distinct_series(self):
        reg = metrics_lib.Registry()
        c = reg.counter('t_by_reason_total', 'x', ('reason',))
        c.labels(reason='full').inc(2)
        c.labels(reason='expired').inc(3)
        parsed = metrics_lib.parse_exposition(reg.expose())
        series = parsed['t_by_reason_total']
        assert series[(('reason', 'full'),)] == 2
        assert series[(('reason', 'expired'),)] == 3

    def test_label_validation(self):
        reg = metrics_lib.Registry()
        c = reg.counter('t_lab_total', 'x', ('a', 'b'))
        with pytest.raises(ValueError, match='unknown labels'):
            c.labels(a='1', nope='2')
        with pytest.raises(ValueError, match='label value'):
            c.labels('only-one')
        with pytest.raises(ValueError, match='has labels'):
            c.inc()  # labeled metric needs .labels(...) first

    def test_label_cardinality_overflow_folds(self):
        reg = metrics_lib.Registry()
        c = metrics_lib.Counter('t_card_total', 'x', ('k',),
                                max_series=4)
        reg.register(c)
        for i in range(10):
            c.labels(k=f'v{i}').inc()
        series = c.series()
        # 4 real series + one overflow bucket, never 10.
        assert len(series) == 5
        overflow = series[('_overflow_',)]
        assert overflow[0] == 6  # the folded increments

    def test_get_or_create_and_conflict(self):
        reg = metrics_lib.Registry()
        a = reg.counter('t_same_total', 'x')
        b = reg.counter('t_same_total', 'x')
        assert a is b
        with pytest.raises(ValueError, match='already registered'):
            reg.gauge('t_same_total', 'x')
        with pytest.raises(ValueError, match='already registered'):
            reg.counter('t_same_total', 'x', ('extra',))

    def test_concurrent_increments_from_threads(self):
        reg = metrics_lib.Registry()
        c = reg.counter('t_race_total', 'x')
        h = reg.histogram('t_race_seconds', 'x', buckets=(0.5, 1.0))

        def worker():
            for _ in range(1000):
                c.inc()
                h.observe(0.25)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000
        assert h.count == 8000
        assert h.bucket_counts() == [8000, 0, 0]


class TestHistogram:

    def test_bucket_boundaries_le_inclusive(self):
        reg = metrics_lib.Registry()
        h = reg.histogram('t_hist_seconds', 'x', buckets=(0.1, 1.0, 5.0))
        # On-boundary observations land IN the bucket (Prometheus `le`
        # is <=); above the top bound lands in +Inf.
        for v in (0.1, 0.05, 1.0, 4.9, 5.0, 100.0):
            h.observe(v)
        assert h.bucket_counts() == [2, 1, 2, 1]
        assert h.count == 6
        assert h.sum == pytest.approx(111.05)

    def test_exposition_cumulative_with_inf(self):
        reg = metrics_lib.Registry()
        h = reg.histogram('t_exp_seconds', 'x', buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 99.0):
            h.observe(v)
        parsed = metrics_lib.parse_exposition(reg.expose())
        buckets = parsed['t_exp_seconds_bucket']
        assert buckets[(('le', '1'),)] == 1
        assert buckets[(('le', '2'),)] == 2
        assert buckets[(('le', '+Inf'),)] == 3
        assert parsed['t_exp_seconds_count'][()] == 3
        assert parsed['t_exp_seconds_sum'][()] == pytest.approx(101.0)

    def test_rejects_bad_buckets(self):
        with pytest.raises(ValueError):
            metrics_lib.Histogram('t_bad', 'x', buckets=())
        with pytest.raises(ValueError, match='duplicate'):
            metrics_lib.Histogram('t_bad2', 'x', buckets=(1.0, 1.0))


def test_label_value_escaping_round_trip():
    reg = metrics_lib.Registry()
    c = reg.counter('t_escape_total', 'x', ('path',))
    tricky = 'a"b\\c\nd'
    c.labels(path=tricky).inc()
    parsed = metrics_lib.parse_exposition(reg.expose())
    assert parsed['t_escape_total'][(('path', tricky),)] == 1


def test_exposition_http_server():
    reg = metrics_lib.Registry()
    reg.counter('t_http_total', 'x').inc(3)
    port, shutdown = metrics_lib.start_exposition_server(registry=reg)
    try:
        resp = requests.get(f'http://127.0.0.1:{port}/metrics',
                            timeout=10)
        assert resp.status_code == 200
        assert 'text/plain' in resp.headers['Content-Type']
        parsed = metrics_lib.parse_exposition(resp.text)
        assert parsed['t_http_total'][()] == 3
        assert requests.get(f'http://127.0.0.1:{port}/nope',
                            timeout=10).status_code == 404
    finally:
        shutdown()


# ----------------------------------------------------------------- tracing


class TestRequestSpan:

    def test_phases_recorded(self):
        span = tracing.RequestSpan('req-1')
        span.mark_admitted()
        span.mark_prefill_chunk(0.01)
        span.mark_prefill_chunk(0.02)
        assert span.mark_token() is None      # first token -> TTFT
        gap = span.mark_token()
        assert gap is not None and gap >= 0
        span.finish('ok')
        d = span.to_dict()
        assert d['request_id'] == 'req-1'
        assert d['queue_wait_ms'] is not None
        assert d['prefill_chunks'] == 2
        assert d['prefill_ms'] == pytest.approx(30.0, abs=0.5)
        assert d['ttft_ms'] is not None
        assert d['tokens'] == 2
        assert d['total_ms'] is not None
        assert d['status'] == 'ok'

    def test_finish_idempotent(self):
        span = tracing.RequestSpan()
        span.finish('ok')
        total = span.total_s
        span.finish('error')
        assert span.status == 'ok' and span.total_s == total

    def test_store_bounded_and_lookup(self):
        store = tracing.SpanStore(maxlen=3)
        for i in range(5):
            s = tracing.RequestSpan(f'r{i}')
            s.finish()
            store.add(s)
        assert len(store) == 3
        assert store.get('r0') is None           # aged out
        assert store.get('r4')['request_id'] == 'r4'
        recent = store.recent(2)
        assert [s['request_id'] for s in recent] == ['r4', 'r3']

    def test_ids_unique(self):
        ids = {tracing.new_request_id() for _ in range(100)}
        assert len(ids) == 100


# ----------------------------------------------------------- timeline fixes


class TestTimelineSatellite:

    def test_programmatic_start_then_save(self, tmp_path, monkeypatch):
        path = str(tmp_path / 'trace.json')
        monkeypatch.setattr(timeline, '_events', [])
        monkeypatch.setattr(timeline, '_enabled_path', None)
        monkeypatch.setattr(timeline, '_atexit_registered', True)
        timeline.start(path)
        with timeline.Event('late-span'):
            pass
        timeline.add_complete_event('retro', 123.0, 0.5, {'k': 'v'})
        timeline.save_timeline()
        events = json.load(open(path))['traceEvents']
        names = [e['name'] for e in events]
        assert 'late-span' in names and 'retro' in names
        retro = next(e for e in events if e['name'] == 'retro')
        assert retro['ph'] == 'X' and retro['dur'] == 500000
        monkeypatch.setattr(timeline, '_enabled_path', None)

    def test_env_checked_after_import(self, tmp_path, monkeypatch):
        """SKYTPU_TIMELINE_FILE set AFTER import still records + dumps
        (it was read once at import before)."""
        path = str(tmp_path / 'late_env.json')
        monkeypatch.setattr(timeline, '_events', [])
        monkeypatch.setattr(timeline, '_enabled_path', None)
        monkeypatch.setenv('SKYTPU_TIMELINE_FILE', path)
        with timeline.Event('env-span'):
            pass
        timeline.save_timeline()
        events = json.load(open(path))['traceEvents']
        assert any(e['name'] == 'env-span' for e in events)

    def test_atexit_registered_exactly_once(self, tmp_path, monkeypatch):
        calls = []
        monkeypatch.setattr(timeline, '_atexit_registered', False)
        monkeypatch.setattr(timeline.atexit, 'register',
                            lambda fn: calls.append(fn))
        timeline.start(str(tmp_path / 'a.json'))
        timeline.start(str(tmp_path / 'b.json'))
        timeline.start(str(tmp_path / 'c.json'))
        assert calls == [timeline.save_timeline]
        monkeypatch.setattr(timeline, '_enabled_path', None)


# ---------------------------------------------- live server round trip


@pytest.fixture(scope='module')
def cb_server():
    """One continuous-batching model server shared by the round-trip
    tests (the jit warmup dominates; module scope amortizes it)."""
    from skypilot_tpu.serve import model_server
    srv = model_server.ModelServer('tiny', max_len=64, max_batch=2,
                                   continuous_batching=True)
    port, shutdown = model_server.start_background(srv)
    yield srv, port
    shutdown()
    srv.close()


def test_metrics_endpoint_round_trip(cb_server):
    """GET /metrics on a live model_server: valid Prometheus text that
    parses, with the queue-wait and ITL histograms present and the
    engine counters advancing across requests."""
    _, port = cb_server
    url = f'http://127.0.0.1:{port}'
    before = metrics_lib.parse_exposition(
        requests.get(url + '/metrics', timeout=30).text)
    resp = requests.post(url + '/generate',
                         json={'prompt_ids': [[1, 2, 3]],
                               'max_new_tokens': 4}, timeout=300)
    assert resp.status_code == 200
    after_text = requests.get(url + '/metrics', timeout=30).text
    assert after_text.startswith('# HELP')
    after = metrics_lib.parse_exposition(after_text)
    # Histograms the acceptance criteria name.
    assert any(k.startswith('skytpu_engine_queue_wait_seconds_bucket')
               for k in after)
    assert any(k.startswith('skytpu_engine_itl_seconds_bucket')
               for k in after)

    def total(parsed, name):
        return sum((parsed.get(name) or {}).values())

    assert (total(after, 'skytpu_engine_decode_tokens_total') >=
            total(before, 'skytpu_engine_decode_tokens_total') + 4)
    assert (total(after, 'skytpu_engine_queue_wait_seconds_count') >
            total(before, 'skytpu_engine_queue_wait_seconds_count'))
    assert total(after, 'skytpu_engine_slots') == 2


def test_request_id_span_via_stats_and_timeline(cb_server, tmp_path,
                                                monkeypatch):
    """A request submitted with X-SkyTPU-Request-Id yields a span
    record (queue/prefill/TTFT/decode) retrievable via stats() and
    visible in the Chrome-trace timeline file."""
    srv, port = cb_server
    trace_path = str(tmp_path / 'serve_trace.json')
    monkeypatch.setattr(timeline, '_events', [])
    monkeypatch.setattr(timeline, '_atexit_registered', True)
    timeline.start(trace_path)
    try:
        rid = 'trace-me-123'
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate',
            json={'prompt_ids': [[5, 6, 7, 8]], 'max_new_tokens': 4},
            headers={tracing.REQUEST_ID_HEADER: rid}, timeout=300)
        assert resp.status_code == 200
        # The id round-trips onto the response.
        assert resp.headers[tracing.REQUEST_ID_HEADER] == rid
        engine = srv._engine  # pylint: disable=protected-access
        # Retrievable via stats() ...
        stats = engine.stats()
        spans = {s['request_id']: s for s in stats['recent_spans']}
        assert rid in spans, stats['recent_spans']
        span = spans[rid]
        for key in ('queue_wait_ms', 'prefill_ms', 'ttft_ms',
                    'total_ms'):
            assert span[key] is not None and span[key] >= 0, (key, span)
        assert span['tokens'] == 4
        assert span['status'] == 'ok'
        # ... and via the direct lookup.
        assert engine.span(rid)['request_id'] == rid
        # ... and in the Chrome-trace timeline file.
        timeline.save_timeline()
        events = json.load(open(trace_path))['traceEvents']
        names = [e['name'] for e in events]
        assert f'request:{rid}' in names
        assert f'request:{rid}/decode' in names
    finally:
        monkeypatch.setattr(timeline, '_enabled_path', None)


def test_request_id_generated_when_absent(cb_server):
    _, port = cb_server
    resp = requests.post(
        f'http://127.0.0.1:{port}/generate',
        json={'prompt_ids': [[9, 8]], 'max_new_tokens': 2}, timeout=300)
    assert resp.status_code == 200
    rid = resp.headers[tracing.REQUEST_ID_HEADER]
    assert rid  # server minted one


def test_async_front_metrics_and_request_id(cb_server):
    """The asyncio front serves /metrics and honors the header too."""
    from skypilot_tpu.serve import async_server
    srv, _ = cb_server
    port, shutdown = async_server.start_background(srv)
    try:
        text = requests.get(f'http://127.0.0.1:{port}/metrics',
                            timeout=30).text
        parsed = metrics_lib.parse_exposition(text)
        assert 'skytpu_engine_ticks_total' in parsed
        rid = 'async-abc'
        resp = requests.post(
            f'http://127.0.0.1:{port}/generate',
            json={'prompt_ids': [[4, 2]], 'max_new_tokens': 2},
            headers={tracing.REQUEST_ID_HEADER: rid}, timeout=300)
        assert resp.status_code == 200
        assert resp.headers[tracing.REQUEST_ID_HEADER] == rid
        assert srv._engine.span(rid) is not None  # pylint: disable=protected-access
    finally:
        shutdown()


# ------------------------------------------------- training telemetry


class TestCallbacksSplit:

    @pytest.fixture(autouse=True)
    def _fresh(self, monkeypatch, _isolated_home):
        from skypilot_tpu.callbacks import base
        monkeypatch.setenv(base.ENV_LOG_DIR,
                           str(_isolated_home / 'bench_logs'))
        monkeypatch.setattr(base, '_instance', None)
        yield

    def test_compute_vs_data_wait_split(self):
        """Regression (ISSUE 3 satellite): inter-end seconds_per_step
        folds data gaps into step time; the split view must not."""
        from skypilot_tpu.callbacks import base
        cb = base.init()
        # Synthetic timeline: 1s steps separated by 2s data stalls.
        cb.step_begins = [0.0, 3.0, 6.0]
        cb.step_ends = [1.0, 4.0, 7.0]
        summary = cb.summary()
        # Legacy metric: (7 - 1) / 2 = 3s — compute AND wait.
        assert summary['seconds_per_step'] == pytest.approx(3.0)
        # Split: pure compute is 1s/step, the 4s of gaps are reported
        # separately.
        assert summary['compute_seconds_per_step'] == pytest.approx(1.0)
        assert summary['data_wait_seconds'] == pytest.approx(4.0)

    def test_tokens_per_s_and_peak_memory(self):
        from skypilot_tpu.callbacks import base
        cb = base.init(tokens_per_step=1000)
        cb.step_begins = [0.0, 10.0]
        cb.step_ends = [2.0, 10.5]
        summary = cb.summary()
        # Steady state (first step excluded): 0.5s compute -> 2000 t/s.
        assert summary['tokens_per_s'] == pytest.approx(2000.0)
        base.record_peak_memory(123456)
        assert cb.summary()['peak_memory_bytes'] == 123456

    def test_prefetch_reports_data_wait(self):
        """A slow producer shows up in prefetch_wait_seconds and the
        data-wait counter."""
        import time as _time

        from skypilot_tpu.callbacks import base
        from skypilot_tpu.data import prefetch
        cb = base.init()

        def slow_src():
            for i in range(3):
                _time.sleep(0.05)
                yield {'x': i}

        # No sharding/jax needed: plain objects pass through tree_map.
        items = list(prefetch.DevicePrefetcher(iter(slow_src())))
        assert len(items) == 3
        assert cb.prefetch_wait_seconds > 0

    def test_late_tokens_per_step_adopted(self):
        from skypilot_tpu.callbacks import base
        base.init()
        cb = base.init(tokens_per_step=64)
        assert cb.tokens_per_step == 64


# ----------------------------------------------- LB bounded timestamps


class TestLoadBalancerSatellite:

    def test_timestamps_bounded_on_sync_failure(self, monkeypatch):
        from skypilot_tpu.serve import load_balancer
        monkeypatch.setenv('SKYTPU_LB_MAX_PENDING_TIMESTAMPS', '50')
        lb = load_balancer.SkyServeLoadBalancer('http://127.0.0.1:1')

        def boom(*args, **kwargs):
            raise requests.ConnectionError('controller down')

        monkeypatch.setattr(load_balancer.requests, 'post', boom)
        for i in range(80):
            lb.request_timestamps.append(float(i))
        lb._sync_with_controller()  # pylint: disable=protected-access
        # Bounded drop-oldest: newest 50 kept, 30 counted as dropped.
        assert len(lb.request_timestamps) == 50
        assert lb.request_timestamps[0] == 30.0
        assert lb.dropped_timestamps == 30
        # Repeated failures keep it bounded (samples accrue between
        # sync attempts).
        for i in range(40):
            lb.request_timestamps.append(float(100 + i))
        lb._sync_with_controller()  # pylint: disable=protected-access
        assert len(lb.request_timestamps) == 50
        assert lb.dropped_timestamps == 70

    def test_sync_failure_warns_with_backoff(self, monkeypatch):
        from skypilot_tpu.serve import load_balancer
        lb = load_balancer.SkyServeLoadBalancer('http://127.0.0.1:1')
        monkeypatch.setattr(
            load_balancer.requests, 'post',
            lambda *a, **k: (_ for _ in ()).throw(
                requests.ConnectionError('down')))
        warnings, infos = [], []
        monkeypatch.setattr(load_balancer.logger, 'warning',
                            lambda msg, *a: warnings.append(msg))
        monkeypatch.setattr(load_balancer.logger, 'info',
                            lambda msg, *a: infos.append(msg))
        for _ in range(10):
            lb._sync_with_controller()  # pylint: disable=protected-access
        # WARNING at attempts 1, 2, 4, 8 — not 10 copies of the spam.
        assert len(warnings) == 4
        # Recovery logs once at INFO and resets the backoff.
        monkeypatch.setattr(
            load_balancer.requests, 'post',
            lambda *a, **k: type(
                'R', (), {'json': lambda self:
                          {'ready_replica_urls': []}})())
        lb._sync_with_controller()  # pylint: disable=protected-access
        assert len(infos) == 1 and 'recovered' in infos[0]
        assert lb._sync_failures == 0  # pylint: disable=protected-access
