"""Auxiliary-subsystem tests: timeline tracing, admin policy hook,
autostop config persistence (SURVEY.md §5 — tracing, config/flag
system, failure handling building blocks)."""
from __future__ import annotations

import json
import os

import pytest

from skypilot_tpu import admin_policy
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.utils import timeline


class TestTimeline:

    def test_spans_written_as_chrome_trace(self, _isolated_home,
                                           monkeypatch):
        path = str(_isolated_home / 'trace.json')
        monkeypatch.setenv('SKYTPU_TIMELINE_FILE', path)
        monkeypatch.setattr(timeline, '_enabled_path', path)
        monkeypatch.setattr(timeline, '_events', [])

        with timeline.Event('provision', 'cluster c1'):
            pass

        @timeline.event
        def sync_workdir():
            return 42

        assert sync_workdir() == 42
        timeline.save_timeline()
        with open(path, encoding='utf-8') as f:
            trace = json.load(f)
        events = trace['traceEvents'] if isinstance(trace, dict) else trace
        names = [e['name'] for e in events]
        assert any('provision' in n for n in names)
        assert any('sync_workdir' in n for n in names)
        phases = {e['ph'] for e in events}
        assert {'B', 'E'} <= phases

    def test_disabled_is_noop(self, monkeypatch):
        monkeypatch.setattr(timeline, '_enabled_path', None)
        events_before = list(timeline._events)  # pylint: disable=protected-access
        with timeline.Event('x'):
            pass
        assert timeline._events == events_before  # pylint: disable=protected-access

    def test_filelock_event_acquires(self, _isolated_home, monkeypatch):
        monkeypatch.setattr(timeline, '_enabled_path', None)
        lock_path = str(_isolated_home / 'x.lock')
        with timeline.FileLockEvent(lock_path):
            assert os.path.exists(lock_path)


class _RejectTpuPolicy(admin_policy.AdminPolicy):

    @classmethod
    def validate_and_mutate(cls, user_request):
        for task in user_request.dag.tasks:
            for res in task.resources:
                if res.tpu_spec is not None:
                    raise exceptions.UserRequestRejectedByPolicy(
                        'TPUs forbidden by org policy.')
        return admin_policy.MutatedUserRequest(dag=user_request.dag)


class _AddLabelPolicy(admin_policy.AdminPolicy):

    @classmethod
    def validate_and_mutate(cls, user_request):
        for task in user_request.dag.tasks:
            task.name = f'org-{task.name}'
        return admin_policy.MutatedUserRequest(dag=user_request.dag)


def _dag_with(resources=None):
    dag = dag_lib.Dag()
    task = task_lib.Task(name='t')
    if resources is not None:
        task.set_resources(resources)
    dag.add(task)
    return dag


class TestAdminPolicy:

    def _use(self, monkeypatch, cls_name):
        from skypilot_tpu import config as config_lib
        monkeypatch.setattr(
            config_lib, 'get_nested',
            lambda keys, default=None:
            f'{__name__}.{cls_name}' if keys == ('admin_policy',)
            else default)

    def test_no_policy_passthrough(self, monkeypatch):
        from skypilot_tpu import config as config_lib
        monkeypatch.setattr(config_lib, 'get_nested',
                            lambda keys, default=None: None)
        dag = _dag_with()
        assert admin_policy.apply(dag) is dag

    def test_rejecting_policy(self, monkeypatch):
        from skypilot_tpu import Resources
        self._use(monkeypatch, '_RejectTpuPolicy')
        dag = _dag_with(Resources(accelerators='tpu-v5e-8'))
        with pytest.raises(exceptions.UserRequestRejectedByPolicy,
                           match='forbidden'):
            admin_policy.apply(dag)

    def test_mutating_policy(self, monkeypatch):
        self._use(monkeypatch, '_AddLabelPolicy')
        dag = admin_policy.apply(_dag_with())
        assert dag.tasks[0].name == 'org-t'

    def test_bad_policy_path(self, monkeypatch):
        self._use(monkeypatch, 'NoSuchPolicy')
        with pytest.raises(exceptions.UserRequestRejectedByPolicy,
                           match='Could not load'):
            admin_policy.apply(_dag_with())

    def test_non_policy_class_rejected(self, monkeypatch):
        from skypilot_tpu import config as config_lib
        monkeypatch.setattr(
            config_lib, 'get_nested',
            lambda keys, default=None:
            'builtins.dict' if keys == ('admin_policy',) else default)
        with pytest.raises(exceptions.UserRequestRejectedByPolicy,
                           match='not an AdminPolicy'):
            admin_policy.apply(_dag_with())


class TestAutostopLib:

    def test_round_trip_and_enabled(self, _isolated_home):
        autostop_lib.set_autostop(30, down=True, provider_name='local',
                                  cluster_name='c1')
        cfg = autostop_lib.get_autostop_config()
        assert cfg is not None
        assert cfg.autostop_idle_minutes == 30
        assert cfg.down and cfg.enabled
        assert cfg.provider_name == 'local'

        autostop_lib.set_autostop(-1, down=False, provider_name='local',
                                  cluster_name='c1')
        cfg = autostop_lib.get_autostop_config()
        assert cfg is not None and not cfg.enabled

    def test_last_active_advances(self, _isolated_home):
        autostop_lib.set_last_active_time_to_now()
        t1 = autostop_lib.get_last_active_time()
        assert t1 > 0
