"""Asyncio streaming load-balancer tests (no controller needed: the
replica set is injected into `ready_urls` directly; sync-loop behavior
is covered by tests/unit/test_serve.py's controller e2e)."""
from __future__ import annotations

import http.server
import json
import threading
import time

import pytest
import requests

from skypilot_tpu.serve import load_balancer as lb_lib


class _Replica(http.server.ThreadingHTTPServer):
    """Tiny replica: echoes method/path/body; /stream sends timed SSE
    chunks; /slow sleeps before responding."""

    def __init__(self):
        super().__init__(('127.0.0.1', 0), _Handler)
        self.chunk_times = []

    @property
    def url(self):
        return f'http://127.0.0.1:{self.server_address[1]}'


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):
        del args

    def _echo(self):
        length = int(self.headers.get('Content-Length', 0))
        body = self.rfile.read(length) if length else b''
        payload = json.dumps({
            'method': self.command,
            'path': self.path,
            'body': body.decode(),
            'port': self.server.server_address[1],
        }).encode()
        self.send_response(200)
        self.send_header('Content-Length', str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        if self.path == '/stream':
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Transfer-Encoding', 'chunked')
            self.end_headers()
            for i in range(3):
                chunk = f'data: tok{i}\n\n'.encode()
                self.wfile.write(f'{len(chunk):x}\r\n'.encode() + chunk +
                                 b'\r\n')
                self.wfile.flush()
                self.server.chunk_times.append(time.time())
                time.sleep(0.15)
            self.wfile.write(b'0\r\n\r\n')
            return
        self._echo()

    do_POST = _echo


@pytest.fixture()
def replica():
    server = _Replica()
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server
    server.shutdown()


@pytest.fixture()
def lb(replica):
    balancer = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:1',
                                           policy=lb_lib.make_policy(None))
    balancer.ready_urls = [replica.url]
    port = balancer.start()
    yield balancer, port
    balancer.stop()


class TestStreamingProxy:

    def test_get_roundtrip(self, lb):
        _, port = lb
        resp = requests.get(f'http://127.0.0.1:{port}/hello?q=1',
                            timeout=10)
        assert resp.status_code == 200
        data = resp.json()
        assert data['method'] == 'GET'
        assert data['path'] == '/hello?q=1'

    def test_post_body_forwarded(self, lb):
        _, port = lb
        resp = requests.post(f'http://127.0.0.1:{port}/infer',
                             data=b'{"prompt": "hi"}', timeout=10)
        assert resp.json()['body'] == '{"prompt": "hi"}'

    def test_streaming_chunks_arrive_incrementally(self, lb):
        """First SSE chunk must reach the client while the replica is
        still emitting — the proxy may not buffer the response."""
        _, port = lb
        arrive_times = []
        with requests.get(f'http://127.0.0.1:{port}/stream', stream=True,
                          timeout=10) as resp:
            for line in resp.iter_lines():
                if line:
                    arrive_times.append((time.time(), line))
        assert [l for _, l in arrive_times] == [
            b'data: tok0', b'data: tok1', b'data: tok2']
        # tok0 arrived at least one inter-chunk gap before the end.
        assert arrive_times[-1][0] - arrive_times[0][0] > 0.2

    def test_503_when_no_replicas(self, lb):
        balancer, port = lb
        balancer.ready_urls = []
        resp = requests.get(f'http://127.0.0.1:{port}/', timeout=10)
        assert resp.status_code == 503

    def test_502_when_replica_dead(self, lb):
        balancer, port = lb
        balancer.ready_urls = ['http://127.0.0.1:9']  # discard port
        resp = requests.get(f'http://127.0.0.1:{port}/', timeout=10)
        assert resp.status_code == 502

    def test_round_robin_spreads(self, lb, replica):
        balancer, port = lb
        second = _Replica()
        threading.Thread(target=second.serve_forever, daemon=True).start()
        try:
            balancer.ready_urls = [replica.url, second.url]
            ports = {requests.get(f'http://127.0.0.1:{port}/',
                                  timeout=10).json()['port']
                     for _ in range(4)}
            assert ports == {replica.server_address[1],
                             second.server_address[1]}
        finally:
            second.shutdown()

    def test_request_timestamps_recorded(self, lb):
        balancer, port = lb
        requests.get(f'http://127.0.0.1:{port}/', timeout=10)
        assert balancer.request_timestamps

    def test_431_on_oversized_head(self, lb):
        _, port = lb
        resp = requests.get(f'http://127.0.0.1:{port}/',
                            headers={'X-Big': 'x' * (150 * 1024)},
                            timeout=10)
        assert resp.status_code == 431

    def test_expect_100_continue(self, lb):
        """A client that waits for '100 Continue' before sending its
        body must get the interim response (curl's default for large
        POSTs); the proxy answers it itself."""
        import socket
        _, port = lb
        body = b'{"p": 1}'
        with socket.create_connection(('127.0.0.1', port),
                                      timeout=10) as sock:
            sock.sendall(
                b'POST /infer HTTP/1.1\r\n'
                b'Host: x\r\n'
                b'Expect: 100-continue\r\n'
                b'Content-Length: ' + str(len(body)).encode() +
                b'\r\n\r\n')
            sock.settimeout(10)
            interim = sock.recv(1024)
            assert b'100 Continue' in interim
            sock.sendall(body)
            data = b''
            while True:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                data += chunk
        assert b'200' in data.split(b'\r\n', 1)[0]
        assert b'{\\"p\\": 1}' in data or b'"body": "{' in data


class TestLeastConnections:

    def test_select_prefers_idle(self):
        policy = lb_lib.LeastConnectionsPolicy()
        urls = ['http://a', 'http://b']
        policy.acquire('http://a')
        assert policy.select(urls) == 'http://b'
        policy.acquire('http://b')
        policy.acquire('http://b')
        assert policy.select(urls) == 'http://a'
        policy.release('http://a')
        policy.release('http://a')  # over-release never goes negative
        assert policy.select(urls) == 'http://a'

    def test_inflight_released_after_proxy(self, replica):
        policy = lb_lib.LeastConnectionsPolicy()
        balancer = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:1',
                                               policy=policy)
        balancer.ready_urls = [replica.url]
        port = balancer.start()
        try:
            requests.get(f'http://127.0.0.1:{port}/', timeout=10)
            deadline = time.time() + 5
            while time.time() < deadline and policy._inflight:  # pylint: disable=protected-access
                time.sleep(0.05)
            assert not policy._inflight  # pylint: disable=protected-access
        finally:
            balancer.stop()

    def test_released_even_on_dead_replica(self):
        policy = lb_lib.LeastConnectionsPolicy()
        balancer = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:1',
                                               policy=policy)
        balancer.ready_urls = ['http://127.0.0.1:9']
        port = balancer.start()
        try:
            resp = requests.get(f'http://127.0.0.1:{port}/', timeout=10)
            assert resp.status_code == 502
            assert not policy._inflight  # pylint: disable=protected-access
        finally:
            balancer.stop()

    def test_make_policy(self):
        assert isinstance(lb_lib.make_policy('least_connections'),
                          lb_lib.LeastConnectionsPolicy)
        assert isinstance(lb_lib.make_policy('round_robin'),
                          lb_lib.RoundRobinPolicy)
        with pytest.raises(ValueError):
            lb_lib.make_policy('bogus')
