"""Lambda Cloud + REST provisioner (cloud breadth: VERDICT r4 missing
#1).  The API sits behind an injectable transport
(provision/lambda_cloud/instance.py: set_api_runner), so the whole
lifecycle — key registration, quantity launch, all-or-nothing
shortfall sweep, status mapping, terminate — runs without credentials
or network.  Model: tests/unit/test_aws.py / test_azure.py."""
from __future__ import annotations

import pytest

import skypilot_tpu as sky
from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu.clouds import registry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.lambda_cloud import instance as lambda_instance
from skypilot_tpu.utils import dag_utils


class FakeLambdaApi:
    """Minimal account state machine keyed on the REST surface."""

    def __init__(self):
        self.instances = {}   # id -> dict (API /instances shape)
        self.ssh_keys = []    # [{'name', 'public_key'}]
        self.calls = []
        self._next = 0
        # Test knobs:
        self.capacity = 100       # instances the region can grant
        self.fail_launch = None   # (code, message) to reject launches

    def __call__(self, method, path, payload):
        self.calls.append((method, path, payload))
        if (method, path) == ('GET', '/instances'):
            return 200, {'data': list(self.instances.values())}
        if (method, path) == ('GET', '/ssh-keys'):
            return 200, {'data': list(self.ssh_keys)}
        if (method, path) == ('POST', '/ssh-keys'):
            self.ssh_keys.append(dict(payload))
            return 200, {'data': dict(payload)}
        if (method, path) == ('POST', '/instance-operations/launch'):
            if self.fail_launch:
                code, msg = self.fail_launch
                return code, {'error': {'code': 'launch-failed',
                                        'message': msg}}
            ids = []
            for _ in range(min(payload['quantity'], self.capacity)):
                iid = f'i-{self._next:06d}'
                self._next += 1
                self.capacity -= 1
                self.instances[iid] = {
                    'id': iid,
                    'name': payload['name'],
                    'status': 'active',
                    'ip': f'129.1.0.{self._next}',
                    'private_ip': f'10.2.0.{self._next}',
                    'region': {'name': payload['region_name']},
                    'instance_type': {
                        'name': payload['instance_type_name']},
                }
                ids.append(iid)
            return 200, {'data': {'instance_ids': ids}}
        if (method, path) == ('POST', '/instance-operations/terminate'):
            gone = []
            for iid in payload['instance_ids']:
                if iid in self.instances:
                    gone.append(self.instances.pop(iid))
            return 200, {'data': {'terminated_instances': gone}}
        return 404, {'error': {'code': 'not-found', 'message': path}}


@pytest.fixture
def fake_api():
    api = FakeLambdaApi()
    lambda_instance.set_api_runner(api)
    yield api
    lambda_instance.set_api_runner(None)


def _config(cluster='lamc', count=2, itype='gpu_8x_a100_80gb_sxm4'):
    return provision_common.ProvisionConfig(
        provider_name='lambda_cloud', cluster_name=cluster,
        region='us-east-1', zones=[],
        deploy_vars={'instance_type': itype}, count=count)


class TestProvisionLifecycle:

    def test_launch_query_info_terminate(self, fake_api):
        record = lambda_instance.run_instances(_config())
        assert record.provider_name == 'lambda_cloud'
        assert len(record.created_instance_ids) == 2
        # Our public key was registered exactly once.
        assert [k['name'] for k in fake_api.ssh_keys] == ['skypilot-tpu']
        launch = next(c for c in fake_api.calls
                      if c[1] == '/instance-operations/launch')
        assert launch[2]['quantity'] == 2
        assert launch[2]['ssh_key_names'] == ['skypilot-tpu']

        status = lambda_instance.query_instances('lamc')
        assert len(status) == 2
        assert all(s.value == 'UP' for s in status.values())

        info = lambda_instance.get_cluster_info('lamc')
        assert info.ssh_user == 'ubuntu'
        assert [i.tags['rank'] for i in info.instances] == ['0', '1']
        # Rank order is the sorted-id order (stable for the lifetime).
        assert (info.instances[0].instance_id <
                info.instances[1].instance_id)
        assert info.instances[0].external_ip.startswith('129.')

        runners = lambda_instance.get_command_runners(info)
        assert len(runners) == 2

        lambda_instance.terminate_instances('lamc')
        assert lambda_instance.query_instances('lamc') == {}

    def test_idempotent_relaunch_and_mismatch(self, fake_api):
        lambda_instance.run_instances(_config(count=2))
        record = lambda_instance.run_instances(_config(count=2))
        assert record.created_instance_ids == []  # already up
        with pytest.raises(exceptions.ResourcesMismatchError):
            lambda_instance.run_instances(_config(count=3))

    def test_ssh_key_registered_once(self, fake_api):
        lambda_instance.run_instances(_config(cluster='a', count=1))
        lambda_instance.run_instances(_config(cluster='b', count=1))
        posts = [c for c in fake_api.calls
                 if c[:2] == ('POST', '/ssh-keys')]
        assert len(posts) == 1

    def test_shortfall_sweeps_partial_set(self, fake_api):
        """All-or-nothing gang: capacity for 1 of 2 terminates the one
        that came up and raises."""
        fake_api.capacity = 1
        with pytest.raises(exceptions.ProvisionError, match='got 1'):
            lambda_instance.run_instances(_config(count=2))
        assert fake_api.instances == {}

    def test_launch_api_error_surfaces(self, fake_api):
        fake_api.fail_launch = (400, 'Not enough capacity')
        with pytest.raises(exceptions.ProvisionError,
                           match='Not enough capacity'):
            lambda_instance.run_instances(_config())

    def test_no_stop_support(self, fake_api):
        lambda_instance.run_instances(_config(count=1))
        with pytest.raises(exceptions.NotSupportedError):
            lambda_instance.stop_instances('lamc')
        with pytest.raises(exceptions.NotSupportedError):
            lambda_instance.open_ports('lamc', [8080])

    def test_worker_only_terminate_keeps_head(self, fake_api):
        lambda_instance.run_instances(_config(count=3))
        head = lambda_instance.get_cluster_info('lamc').head_instance_id
        lambda_instance.terminate_instances('lamc', worker_only=True)
        left = lambda_instance.query_instances('lamc')
        assert list(left) == [head]

    def test_status_map(self, fake_api):
        lambda_instance.run_instances(_config(count=1))
        inst = next(iter(fake_api.instances.values()))
        from skypilot_tpu.status_lib import ClusterStatus
        for api_status, want in [('active', ClusterStatus.UP),
                                 ('booting', ClusterStatus.INIT),
                                 ('unhealthy', ClusterStatus.INIT),
                                 ('terminating', None)]:
            inst['status'] = api_status
            assert lambda_instance.query_instances('lamc') == {
                inst['id']: want}


class TestLambdaCloud:

    def test_feasibility_gpu_to_instance_type(self):
        lam = registry.CLOUD_REGISTRY['lambda']
        r = sky.Resources(cloud='lambda', accelerators='H100:8')
        launchable, _ = lam.get_feasible_launchable_resources(r)
        assert launchable
        assert launchable[0].instance_type == 'gpu_8x_h100_sxm5'

    def test_tpu_and_spot_not_feasible(self):
        lam = registry.CLOUD_REGISTRY['lambda']
        assert lam.get_feasible_launchable_resources(
            sky.Resources(accelerators='tpu-v5e-8'))[0] == []
        spot = sky.Resources(cloud='lambda', accelerators='A100:1',
                             capacity='spot')
        assert lam.get_feasible_launchable_resources(spot)[0] == []

    def test_pricing_and_no_egress(self):
        assert catalog.get_hourly_cost(
            'lambda', 'gpu_1x_a100_sxm4') == pytest.approx(1.29)
        lam = registry.CLOUD_REGISTRY['lambda']
        assert lam.get_egress_cost(500) == 0.0

    def test_stop_feature_rejected(self):
        from skypilot_tpu.clouds import cloud as cloud_lib
        lam = registry.CLOUD_REGISTRY['lambda']
        with pytest.raises(exceptions.NotSupportedError):
            lam.check_features_are_supported(
                sky.Resources(cloud='lambda'),
                {cloud_lib.CloudImplementationFeatures.STOP})

    def test_credentials_from_keys_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv('HOME', str(tmp_path))
        monkeypatch.delenv('LAMBDA_API_KEY', raising=False)
        lam = registry.CLOUD_REGISTRY['lambda']
        ok, reason = lam.check_credentials()
        assert not ok and 'lambda_keys' in reason
        keys = tmp_path / '.lambda_cloud'
        keys.mkdir()
        (keys / 'lambda_keys').write_text('api_key = secret123\n')
        ok, _ = lam.check_credentials()
        assert ok
        assert lam.get_current_user_identity() == ['lambda:secret12']

    def test_optimizer_prefers_cheapest_gpu_pool(self, enable_all_infra):
        """Lambda's A100 box undercuts the hyperscalers: an
        accelerator-anywhere task lands on Lambda, and blocking it
        falls over to the next pool."""
        task = sky.Task(name='t', run='true')
        task.set_resources({
            sky.Resources(cloud='gcp', accelerators='A100:1'),
            sky.Resources(cloud='lambda', accelerators='A100:1'),
        })
        dag = dag_utils.convert_entrypoint_to_dag(task)
        optimizer_lib.Optimizer.optimize(
            dag, minimize=optimizer_lib.OptimizeTarget.COST, quiet=True)
        first = task.best_resources
        assert str(first.cloud).lower() == 'lambda'
        optimizer_lib.Optimizer.optimize(
            dag, minimize=optimizer_lib.OptimizeTarget.COST,
            blocked_resources=[first], quiet=True)
        assert str(task.best_resources.cloud).lower() == 'gcp'
