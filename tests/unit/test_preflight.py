"""Collective preflight probe (SURVEY §7 item 9): fabric health checks
before committing a job to a slice."""
from __future__ import annotations

import jax
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.parallel import MeshConfig, build_mesh
from skypilot_tpu.parallel import preflight


@pytest.fixture(scope='module')
def mesh():
    return build_mesh(MeshConfig(data=-1, tensor=2),
                      devices=jax.devices()[:8])


def test_probe_reports_all_nontrivial_axes(mesh):
    results = preflight.probe_collectives(mesh, bandwidth_mb=1,
                                          repeats=2)
    assert set(results) == {'data', 'tensor'}
    for stats in results.values():
        assert stats['psum_latency_ms'] > 0
        assert stats['psum_gbps'] > 0
        assert stats['size'] in (2.0, 4.0)


def test_check_passes_on_healthy_fabric(mesh):
    preflight.check_collectives(
        mesh, results=preflight.probe_collectives(mesh, bandwidth_mb=1,
                                                  repeats=2))


def test_check_fails_on_sick_fabric(mesh):
    sick = {'data': {'size': 4.0, 'psum_latency_ms': 1e9,
                     'psum_gbps': 1e-6}}
    with pytest.raises(exceptions.SkyTpuError, match='preflight'):
        preflight.check_collectives(mesh, results=sick)
