"""Continuous profiling plane tests (ISSUE 18 tentpole).

TickProfiler (exclusive phase laps, bounded ring, idle-tick skip,
disable gate), the recompile sentinel (warm-up compiles free,
steady-state recompiles journaled + counted exactly once), the
collapsed-stack / Chrome-trace exports, `/profile` on BOTH HTTP
fronts, the sharpened MFU numerator, and the ≤3% overhead budget.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import pytest
import requests

from skypilot_tpu.models import configs
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import profiling
from skypilot_tpu.serve import async_server, model_server


class FakeClock:
    """Deterministic monotonic clock: every read advances by `step`
    unless ticks are queued explicitly."""

    def __init__(self, step: float = 1.0) -> None:
        self.now = 0.0
        self.step = step
        self.queued = []

    def __call__(self) -> float:
        self.now += self.queued.pop(0) if self.queued else self.step
        return self.now


class RecordingJournal:
    def __init__(self) -> None:
        self.events = []

    def append(self, name, **fields) -> None:
        self.events.append((name, fields))


def _profiler(**kw):
    kw.setdefault('clock', FakeClock())
    kw.setdefault('memory_cb', lambda: None)
    kw.setdefault('disabled', False)
    return profiling.TickProfiler(**kw)


class TestTickProfiler:

    def test_laps_are_exclusive_and_one_read_each(self):
        clock = FakeClock(step=1.0)
        prof = _profiler(clock=clock)
        prof.begin_tick()                       # t=1
        prof.lap('handoff', record=False)       # t=2, not attributed
        prof.lap('admit')                       # t=3: admit gets 1s
        prof.lap('decode-step')                 # t=4: decode gets 1s
        prof.end_tick()
        snap = prof.snapshot()
        assert snap['ticks'] == 1
        assert set(snap['phases']) == {'admit', 'decode-step'}
        assert snap['phases']['admit']['total_s'] == pytest.approx(1.0)
        assert snap['phases']['decode-step']['total_s'] == \
            pytest.approx(1.0)
        # The unrecorded handoff lap still advanced the lap clock, so
        # its second was attributed to NO phase (phases sum < tick).
        [rec] = snap['ring']
        assert rec['dur_s'] == pytest.approx(3.0)
        assert sum(d for _, _, d in rec['phases']) == pytest.approx(2.0)

    def test_idle_ticks_never_enter_the_ring(self):
        prof = _profiler()
        for _ in range(5):
            prof.begin_tick()
            prof.lap('admit', record=False)     # machinery ran, no work
            prof.end_tick()
        assert prof.ticks == 0
        assert prof.snapshot()['ring'] == []

    def test_ring_is_bounded_but_aggregates_are_cumulative(self):
        prof = _profiler(ring_ticks=4)
        for _ in range(10):
            prof.begin_tick()
            prof.lap('decode-step')
            prof.end_tick()
        snap = prof.snapshot()
        assert len(snap['ring']) == 4
        assert snap['ticks'] == 10
        assert snap['phases']['decode-step']['count'] == 10

    def test_disable_gate_is_a_noop(self):
        prof = _profiler(disabled=True)
        prof.begin_tick()
        prof.lap('decode-step')
        prof.end_tick()
        snap = prof.snapshot()
        assert snap['enabled'] is False
        assert snap['ticks'] == 0 and snap['ring'] == []

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_PROFILE_RING_TICKS', '7')
        monkeypatch.setenv('SKYTPU_PROFILE_DISABLE', '1')
        prof = profiling.TickProfiler(memory_cb=lambda: None)
        assert prof.ring_ticks == 7
        assert prof.disabled is True

    def test_quantiles_over_the_ring(self):
        clock = FakeClock(step=0.0)
        prof = _profiler(clock=clock, ring_ticks=128)
        for dur in (1.0, 2.0, 3.0, 4.0):
            clock.queued = [0.0, dur]          # begin, lap
            prof.begin_tick()
            prof.lap('sample')
            prof.end_tick()
        agg = prof.snapshot()['phases']['sample']
        assert agg['p50_s'] == pytest.approx(3.0)
        assert agg['max_s'] == pytest.approx(4.0)
        assert agg['total_s'] == pytest.approx(10.0)

    def test_memory_watermark_and_dead_backend(self):
        mems = [100, 300, 200]
        prof = _profiler(memory_cb=lambda: mems.pop(0) if mems else None)
        for _ in range(3):
            prof.begin_tick()
            prof.lap('decode-step')
            prof.end_tick()
        snap = prof.snapshot()
        assert snap['device_memory']['watermark_bytes'] == 300
        assert snap['device_memory']['last_bytes'] == 200
        # Backend went dark: the profiler stops asking (no raise).
        prof.begin_tick()
        prof.lap('decode-step')
        prof.end_tick()
        assert prof._mem_dead is True


def _counter_value(name, **labels):
    parsed = metrics_lib.parse_exposition(metrics_lib.expose())
    want = set(labels.items())
    for got_labels, value in parsed.get(name, {}).items():
        if want <= set(got_labels):
            return value
    return 0.0


class TestRecompileSentinel:

    def test_warmup_compiles_are_free_steady_trips_exactly_once(self):
        journal = RecordingJournal()
        sentinel = profiling.RecompileSentinel(
            steady_after=8, journal_factory=lambda: journal,
            disabled=False)
        fn = sentinel.wrap('step', jax.jit(lambda x: x * 2))
        before = _counter_value('skytpu_engine_recompiles_total',
                                fn='step')
        # Warm-up compile + a steady run of identical shapes.
        for _ in range(12):
            fn(jnp.ones((4,), jnp.float32))
        snap = sentinel.snapshot()['fns']['step']
        assert snap['compiles'] == 1
        assert snap['steady_recompiles'] == 0
        assert journal.events == []
        # Shape-buster after a quiet streak: exactly one detection.
        fn(jnp.ones((5,), jnp.float32))
        snap = sentinel.snapshot()['fns']['step']
        assert snap['compiles'] == 2
        assert snap['steady_recompiles'] == 1
        [(event, fields)] = journal.events
        assert event == 'recompile_detected'
        assert fields['fn'] == 'step'
        assert 'float32[5]' in fields['shapes']
        assert fields['quiet_calls'] >= 8
        after = _counter_value('skytpu_engine_recompiles_total',
                               fn='step')
        assert after == before + 1
        # The new shape is now cached: steady state again, no retrips.
        for _ in range(12):
            fn(jnp.ones((5,), jnp.float32))
        assert sentinel.snapshot()['fns']['step'][
            'steady_recompiles'] == 1
        assert len(journal.events) == 1

    def test_immediate_reshape_is_warmup_not_steady(self):
        journal = RecordingJournal()
        sentinel = profiling.RecompileSentinel(
            steady_after=8, journal_factory=lambda: journal,
            disabled=False)
        fn = sentinel.wrap('prefill', jax.jit(lambda x: x + 1))
        # Back-to-back new shapes (bucketed prefill warm-up): compiles
        # counted, but none had a quiet streak -> zero steady.
        for n in (1, 2, 3, 4):
            fn(jnp.ones((n,), jnp.float32))
        snap = sentinel.snapshot()['fns']['prefill']
        assert snap['compiles'] == 4
        assert snap['steady_recompiles'] == 0
        assert journal.events == []

    def test_signature_fallback_for_uncached_callables(self):
        sentinel = profiling.RecompileSentinel(
            steady_after=2, journal_factory=RecordingJournal,
            disabled=False)
        fn = sentinel.wrap('plain', lambda x: x)   # no _cache_size()
        for _ in range(5):
            fn(jnp.ones((3,), jnp.float32))
        fn(jnp.ones((9,), jnp.float32))
        snap = sentinel.snapshot()['fns']['plain']
        assert snap['compiles'] == 2
        assert snap['steady_recompiles'] == 1

    def test_disabled_wrap_is_identity(self):
        sentinel = profiling.RecompileSentinel(disabled=True)
        fn = lambda x: x            # noqa: E731
        assert sentinel.wrap('f', fn) is fn
        assert sentinel.wrap('g', None) is None


class TestExports:

    def _snapshot_all_phases(self):
        clock = FakeClock(step=0.001)
        prof = _profiler(clock=clock, memory_cb=lambda: 4096)
        prof.begin_tick()
        for phase in profiling.PHASES:
            prof.lap(phase)
        prof.end_tick()
        return prof.snapshot()

    def test_collapsed_stacks(self):
        lines = profiling.collapsed_stacks(
            self._snapshot_all_phases()).splitlines()
        assert len(lines) == len(profiling.PHASES)
        for line in lines:
            frame, count = line.rsplit(' ', 1)
            assert frame.startswith('engine;')
            assert int(count) > 0
        assert {l.split(';')[1].split(' ')[0] for l in lines} == \
            set(profiling.PHASES)

    def test_chrome_trace_is_valid_and_carries_all_phases(self):
        trace = profiling.chrome_trace(self._snapshot_all_phases(),
                                       pid=3)
        blob = json.loads(json.dumps(trace))   # JSON-serializable
        assert blob['displayTimeUnit'] == 'ms'
        events = blob['traceEvents']
        bars = [e for e in events if e['ph'] == 'X']
        assert {e['name'] for e in bars} == set(profiling.PHASES)
        for e in bars:
            assert e['dur'] > 0 and e['ts'] > 0 and e['pid'] == 3
        [mem] = [e for e in events if e['ph'] == 'C']
        assert mem['args']['bytes_in_use'] == 4096


@pytest.fixture(scope='module')
def profiled_server():
    srv = model_server.ModelServer('tiny', max_len=64, max_batch=2,
                                   continuous_batching=True)
    yield srv
    srv.close()


class TestProfileEndpoint:

    def _check_payload(self, payload):
        prof = payload['profile']
        assert prof['enabled'] is True
        assert prof['ticks'] > 0
        assert 'decode-step' in prof['phases']
        # Steady-state must be clean on a well-behaved run.
        assert prof['recompiles']['steady_recompiles_total'] == 0
        assert 'step' in prof['recompiles']['fns']
        assert prof['pipelined'] is True

    def test_threaded_front(self, profiled_server):
        port, shutdown = model_server.start_background(profiled_server)
        try:
            gen = requests.post(f'http://127.0.0.1:{port}/generate',
                                json={'prompt_ids': [[3, 1, 4]],
                                      'max_new_tokens': 4},
                                timeout=120)
            assert gen.status_code == 200, gen.text
            resp = requests.get(f'http://127.0.0.1:{port}/profile',
                                timeout=10)
        finally:
            shutdown()
        assert resp.status_code == 200
        self._check_payload(resp.json())

    def test_async_front(self, profiled_server):
        port, shutdown = async_server.start_background(profiled_server)
        try:
            resp = requests.get(f'http://127.0.0.1:{port}/profile',
                                timeout=10)
        finally:
            shutdown()
        assert resp.status_code == 200
        self._check_payload(resp.json())


class TestServeProfileCli:

    def test_export_trace_carries_all_phases(self, tmp_path,
                                             monkeypatch):
        """`sky serve profile --export-trace` against a replica whose
        ring saw every phase writes a valid Chrome trace with all
        eight phase bars."""
        import http.server
        import threading

        from click.testing import CliRunner

        from skypilot_tpu import cli, serve

        clock = FakeClock(step=0.001)
        prof = profiling.TickProfiler(ring_ticks=16, disabled=False,
                                      memory_cb=lambda: 2048,
                                      clock=clock)
        prof.begin_tick()
        for phase in profiling.PHASES:
            prof.lap(phase)
        prof.end_tick()
        sentinel = profiling.RecompileSentinel(
            disabled=False, journal_factory=RecordingJournal)
        snap = prof.snapshot()
        snap['recompiles'] = sentinel.snapshot()
        payload = json.dumps({'status': 'ok', 'profile': snap}).encode()

        class Handler(http.server.BaseHTTPRequestHandler):

            def do_GET(self):          # noqa: N802
                self.send_response(200)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length',
                                 str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        httpd = http.server.ThreadingHTTPServer(('127.0.0.1', 0),
                                                Handler)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        port = httpd.server_address[1]
        record = {'name': 'svc', 'status': 'READY',
                  'load_balancer_port': None,
                  'replicas': [{'replica_id': 1, 'role': 'mixed',
                                'status': 'READY',
                                'url': f'http://127.0.0.1:{port}'}]}
        monkeypatch.setattr(serve, 'status', lambda names=None: [record])
        out_path = tmp_path / 'tick.json'
        try:
            result = CliRunner().invoke(
                cli.cli, ['serve', 'profile', 'svc',
                          '--export-trace', str(out_path)])
        finally:
            httpd.shutdown()
            httpd.server_close()
        assert result.exit_code == 0, result.output
        assert 'steady-state recompiles: 0' in result.output
        assert 'engine;decode-step' in result.output
        trace = json.loads(out_path.read_text())
        assert trace['displayTimeUnit'] == 'ms'
        bars = [e for e in trace['traceEvents'] if e['ph'] == 'X']
        assert {e['name'] for e in bars} == set(profiling.PHASES)


class TestModelFlopsPerToken:

    def test_computed_path_includes_attention_term(self):
        cfg = configs.get_config('tiny')
        n_params, max_len = 100_000, 64
        attn = 2.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * max_len
        got = model_server.model_flops_per_token(cfg, n_params, max_len)
        assert got == pytest.approx(2.0 * n_params + attn)
        # The attention term is sequence-length dependent.
        longer = model_server.model_flops_per_token(cfg, n_params, 128)
        assert longer - got == pytest.approx(attn)

    def test_env_override_wins_and_non_numeric_falls_back(
            self, monkeypatch):
        cfg = configs.get_config('tiny')
        monkeypatch.setenv('SKYTPU_MODEL_FLOPS_PER_TOKEN', '3.5e9')
        assert model_server.model_flops_per_token(cfg, 1, 64) == 3.5e9
        monkeypatch.setenv('SKYTPU_MODEL_FLOPS_PER_TOKEN', 'banana')
        got = model_server.model_flops_per_token(cfg, 1000, 64)
        assert got == pytest.approx(
            2000 + 2.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * 64)


class TestOverheadBudget:
    """The always-on budget: profile-on vs SKYTPU_PROFILE_DISABLE=1
    may differ by at most 3% of a tick's work.

    Wall-clocking two full workloads head-to-head is hopeless on a
    noisy CI box (run-to-run jitter alone exceeds 3%), so the A/B is
    factored: the profiler's marginal per-tick cost comes from a tight
    on-vs-off microbenchmark of the instrumentation alone (stable —
    both arms are long uniform loops), and the budget is asserted
    against a measured representative tick's compute."""

    TICKS = 4000

    @classmethod
    def _per_tick_cost(cls, prof):
        """Seconds per tick of the instrumentation calls alone, at the
        real call pattern (4 laps + begin/end per tick)."""
        t0 = time.perf_counter()
        for _ in range(cls.TICKS):
            prof.begin_tick()
            prof.lap('handoff', record=False)
            prof.lap('admit')
            prof.lap('decode-step')
            prof.lap('sample')
            prof.end_tick()
        return (time.perf_counter() - t0) / cls.TICKS

    def test_profiler_overhead_within_3_percent(self):
        on = profiling.TickProfiler(disabled=False,
                                    memory_cb=lambda: None)
        off = profiling.TickProfiler(disabled=True,
                                     memory_cb=lambda: None)
        self._per_tick_cost(on), self._per_tick_cost(off)   # warm-up
        marginal = min(self._per_tick_cost(on) -
                       self._per_tick_cost(off) for _ in range(5))
        # A representative tick's work: even the tiny model's decode
        # step is milliseconds; 300us is a conservative floor.
        def tick_work():
            t0 = time.perf_counter()
            assert sum(range(30000)) > 0
            return time.perf_counter() - t0
        work = min(tick_work() for _ in range(20))
        assert marginal <= 0.03 * work, (marginal, work)
        # The profiler's own overhead model stays in the same regime.
        snap = on.snapshot()
        per_tick_model = snap['overhead_s'] / max(1, snap['ticks'])
        assert per_tick_model <= 0.03 * work
