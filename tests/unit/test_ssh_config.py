"""SSHConfigHelper + remote runtime version-skew check (VERDICT r2
missing #5; reference backend_utils.py:399, :2593)."""
from __future__ import annotations

import os

import pytest

from skypilot_tpu.backends import backend_utils


@pytest.fixture
def _fake_home(tmp_path, monkeypatch):
    home = tmp_path / 'home'
    home.mkdir()
    monkeypatch.setenv('HOME', str(home))
    yield home


class TestSSHConfigHelper:

    def test_add_writes_host_blocks_and_include(self, _fake_home):
        backend_utils.SSHConfigHelper.add_cluster(
            'mycluster', ['10.0.0.1', '10.0.0.2'], ssh_user='tpuuser',
            ssh_private_key='/keys/sky-key')
        ssh_config = (_fake_home / '.ssh' / 'config').read_text()
        assert 'Include' in ssh_config
        conf_dir = backend_utils.SSHConfigHelper._ssh_dir()
        conf = open(os.path.join(conf_dir, 'mycluster.conf'),
                    encoding='utf-8').read()
        assert 'Host mycluster\n' in conf
        assert 'Host mycluster-worker1\n' in conf
        assert 'HostName 10.0.0.1' in conf
        assert 'User tpuuser' in conf
        assert 'IdentityFile /keys/sky-key' in conf
        assert backend_utils.SSHConfigHelper.list_clusters() == [
            'mycluster']

    def test_include_prepended_once_and_before_hosts(self, _fake_home):
        ssh_dir = _fake_home / '.ssh'
        ssh_dir.mkdir()
        (ssh_dir / 'config').write_text('Host existing\n  User me\n')
        backend_utils.SSHConfigHelper.add_cluster(
            'c1', ['1.2.3.4'], ssh_user='u', ssh_private_key=None)
        backend_utils.SSHConfigHelper.add_cluster(
            'c2', ['1.2.3.5'], ssh_user='u', ssh_private_key=None)
        content = (ssh_dir / 'config').read_text()
        assert content.count('Include') == 1
        # Include applies globally only before the first Host block.
        assert content.index('Include') < content.index('Host existing')
        assert 'Host existing' in content

    def test_remove_cluster(self, _fake_home):
        backend_utils.SSHConfigHelper.add_cluster(
            'gone', ['1.1.1.1'], ssh_user='u', ssh_private_key=None)
        backend_utils.SSHConfigHelper.remove_cluster('gone')
        assert backend_utils.SSHConfigHelper.list_clusters() == []
        # Idempotent.
        backend_utils.SSHConfigHelper.remove_cluster('gone')

    def test_proxy_command(self, _fake_home):
        backend_utils.SSHConfigHelper.add_cluster(
            'p', ['1.1.1.1'], ssh_user='u', ssh_private_key=None,
            ssh_proxy_command='corkscrew proxy 8080 %h %p')
        conf_dir = backend_utils.SSHConfigHelper._ssh_dir()
        conf = open(os.path.join(conf_dir, 'p.conf'),
                    encoding='utf-8').read()
        assert 'ProxyCommand corkscrew proxy 8080 %h %p' in conf


class _FakeHandle:
    cluster_name = 'c'

    def __init__(self, launched_version):
        if launched_version is not None:
            self.launched_runtime_version = launched_version


class TestVersionSkew:
    """The check compares the version STAMPED on the handle at
    provision time — a local comparison, zero ssh on the exec path."""

    def test_in_sync(self):
        import skypilot_tpu
        handle = _FakeHandle(skypilot_tpu.__version__)
        assert backend_utils.check_remote_runtime_version(handle) is None

    def test_skew_warns(self):
        handle = _FakeHandle('0.0.9')
        warning = backend_utils.check_remote_runtime_version(handle)
        assert warning is not None and '0.0.9' in warning

    def test_prestamp_handle_is_silent(self):
        assert backend_utils.check_remote_runtime_version(
            _FakeHandle(None)) is None
