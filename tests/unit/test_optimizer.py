"""Optimizer dryruns (parity: reference tests/test_optimizer_dryruns.py) —
fully offline via the enable_all_infra fixture."""
from __future__ import annotations

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib

Optimizer = optimizer_lib.Optimizer
OptimizeTarget = optimizer_lib.OptimizeTarget


def _single_task_dag(task):
    dag = dag_lib.Dag()
    dag.add(task)
    return dag


def test_requires_enabled_clouds():
    task = task_lib.Task(name='t')
    with pytest.raises(exceptions.NoCloudAccessError):
        Optimizer.optimize(_single_task_dag(task), quiet=True)


def test_tpu_vs_gpu_fungibility(enable_all_infra):
    """The north-star behavior: TPU and GPU candidates compete on cost."""
    task = task_lib.Task(name='train')
    task.set_resources({
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'),
        resources_lib.Resources(cloud='gcp', accelerators='A100:8'),
    })
    Optimizer.optimize(_single_task_dag(task), quiet=True)
    best = task.best_resources
    # v5e-8 is $9.6/hr vs $29.39/hr for A100:8.
    assert best.tpu_spec is not None and best.tpu_spec.name == 'tpu-v5e-8'


def test_time_target_uses_estimator(enable_all_infra):
    task = task_lib.Task(name='train')
    v5e = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    a100 = resources_lib.Resources(cloud='gcp', accelerators='A100:8')
    task.set_resources({v5e, a100})
    # User says the A100 is 10x faster for this workload.
    task.set_time_estimator(
        lambda r: 600.0 if r.accelerators and 'A100' in r.accelerators else 6000.0)
    Optimizer.optimize(_single_task_dag(task), minimize=OptimizeTarget.TIME,
                       quiet=True)
    assert 'A100' in task.best_resources.accelerators


def test_spot_cheaper_than_on_demand(enable_all_infra):
    task = task_lib.Task(name='t')
    task.set_resources({
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'),
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8',
                                capacity='spot'),
    })
    Optimizer.optimize(_single_task_dag(task), quiet=True)
    assert task.best_resources.use_spot


def test_blocked_resources_failover(enable_all_infra):
    task = task_lib.Task(name='t')
    spot = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8',
                                   capacity='spot')
    od = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    task.set_resources({spot, od})
    launchables = Optimizer.enumerate_launchables(task)
    cheapest = launchables[0][0]
    assert cheapest.use_spot
    Optimizer.optimize(_single_task_dag(task), blocked_resources=[cheapest],
                       quiet=True)
    assert not task.best_resources.use_spot


def test_chain_dag_plan(enable_all_infra):
    with dag_lib.Dag('pipe') as dag:
        train = task_lib.Task(name='train')
        train.set_resources(
            resources_lib.Resources(cloud='gcp', accelerators='tpu-v5p-8'))
        serve = task_lib.Task(name='serve')
        serve.set_resources(
            resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'))
        train >> serve
    Optimizer.optimize(dag, quiet=True)
    assert train.best_resources.tpu_spec.generation == 'v5p'
    assert serve.best_resources.tpu_spec.generation == 'v5e'
    table = optimizer_lib.format_plan_table(
        {t: (t.best_resources, 0.0) for t in dag.tasks},
        OptimizeTarget.COST)
    assert 'tpu-v5p-8' in table and 'tpu-v5e-8' in table


def test_infeasible_raises_with_fuzzy_hint(enable_all_infra):
    task = task_lib.Task(name='t')
    task.set_resources(
        resources_lib.Resources(cloud='gcp', accelerators='A100:5'))
    with pytest.raises(exceptions.ResourcesUnavailableError):
        Optimizer.optimize(_single_task_dag(task), quiet=True)
