"""Optimizer dryruns (parity: reference tests/test_optimizer_dryruns.py) —
fully offline via the enable_all_infra fixture."""
from __future__ import annotations

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib

Optimizer = optimizer_lib.Optimizer
OptimizeTarget = optimizer_lib.OptimizeTarget


def _single_task_dag(task):
    dag = dag_lib.Dag()
    dag.add(task)
    return dag


def test_requires_enabled_clouds():
    task = task_lib.Task(name='t')
    with pytest.raises(exceptions.NoCloudAccessError):
        Optimizer.optimize(_single_task_dag(task), quiet=True)


def test_tpu_vs_gpu_fungibility(enable_all_infra):
    """The north-star behavior: TPU and GPU candidates compete on cost."""
    task = task_lib.Task(name='train')
    task.set_resources({
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'),
        resources_lib.Resources(cloud='gcp', accelerators='A100:8'),
    })
    Optimizer.optimize(_single_task_dag(task), quiet=True)
    best = task.best_resources
    # v5e-8 is $9.6/hr vs $29.39/hr for A100:8.
    assert best.tpu_spec is not None and best.tpu_spec.name == 'tpu-v5e-8'


def test_time_target_uses_estimator(enable_all_infra):
    task = task_lib.Task(name='train')
    v5e = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    a100 = resources_lib.Resources(cloud='gcp', accelerators='A100:8')
    task.set_resources({v5e, a100})
    # User says the A100 is 10x faster for this workload.
    task.set_time_estimator(
        lambda r: 600.0 if r.accelerators and 'A100' in r.accelerators else 6000.0)
    Optimizer.optimize(_single_task_dag(task), minimize=OptimizeTarget.TIME,
                       quiet=True)
    assert 'A100' in task.best_resources.accelerators


def test_spot_cheaper_than_on_demand(enable_all_infra):
    task = task_lib.Task(name='t')
    task.set_resources({
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'),
        resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8',
                                capacity='spot'),
    })
    Optimizer.optimize(_single_task_dag(task), quiet=True)
    assert task.best_resources.use_spot


def test_blocked_resources_failover(enable_all_infra):
    task = task_lib.Task(name='t')
    spot = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8',
                                   capacity='spot')
    od = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    task.set_resources({spot, od})
    launchables = Optimizer.enumerate_launchables(task)
    cheapest = launchables[0][0]
    assert cheapest.use_spot
    Optimizer.optimize(_single_task_dag(task), blocked_resources=[cheapest],
                       quiet=True)
    assert not task.best_resources.use_spot


def test_chain_dag_plan(enable_all_infra):
    with dag_lib.Dag('pipe') as dag:
        train = task_lib.Task(name='train')
        train.set_resources(
            resources_lib.Resources(cloud='gcp', accelerators='tpu-v5p-8'))
        serve = task_lib.Task(name='serve')
        serve.set_resources(
            resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'))
        train >> serve
    Optimizer.optimize(dag, quiet=True)
    assert train.best_resources.tpu_spec.generation == 'v5p'
    assert serve.best_resources.tpu_spec.generation == 'v5e'
    table = optimizer_lib.format_plan_table(
        {t: (t.best_resources, 0.0) for t in dag.tasks},
        OptimizeTarget.COST)
    assert 'tpu-v5p-8' in table and 'tpu-v5e-8' in table


def test_infeasible_raises_with_fuzzy_hint(enable_all_infra):
    task = task_lib.Task(name='t')
    task.set_resources(
        resources_lib.Resources(cloud='gcp', accelerators='A100:5'))
    with pytest.raises(exceptions.ResourcesUnavailableError):
        Optimizer.optimize(_single_task_dag(task), quiet=True)


def _diamond_dag():
    """A → {B, C} → D (non-chain)."""
    with dag_lib.Dag('diamond') as dag:
        a = task_lib.Task(name='prep')
        b = task_lib.Task(name='train-b')
        c = task_lib.Task(name='train-c')
        d = task_lib.Task(name='eval')
        for t in (a, b, c, d):
            dag.add(t)
        dag.add_edge(a, b)
        dag.add_edge(a, c)
        dag.add_edge(b, d)
        dag.add_edge(c, d)
    return dag, (a, b, c, d)


def test_general_dag_cost_plan(enable_all_infra):
    """Non-chain DAGs are optimized (parity: reference _optimize_by_ilp)
    instead of rejected; every task gets best_resources."""
    dag, tasks = _diamond_dag()
    assert not dag.is_chain()
    for t in tasks:
        t.set_resources({
            resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8'),
            resources_lib.Resources(cloud='gcp', accelerators='A100:8'),
        })
    Optimizer.optimize(dag, quiet=True)
    for t in tasks:
        assert t.best_resources is not None
        # v5e is strictly cheaper, so exact search must pick it everywhere.
        assert t.best_resources.tpu_spec is not None


def test_general_dag_egress_prefers_colocation(enable_all_infra):
    """With large intermediate outputs, cross-cloud hops must be avoided
    even when the remote candidate is marginally cheaper per hour."""
    dag, tasks = _diamond_dag()
    a, b, c, d = tasks
    gcp = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    aws = resources_lib.Resources(cloud='aws', accelerators='A10G:8')
    for t in tasks:
        t.set_resources({gcp, aws})
        t.estimated_outputs_size_gigabytes = 500.0
    Optimizer.optimize(dag, quiet=True)
    clouds = {t.best_resources.cloud.name for t in tasks}
    assert len(clouds) == 1, f'split placement pays egress: {clouds}'


def test_general_dag_time_target(enable_all_infra):
    """TIME minimizes the critical path: the slow branch must get the
    fast accelerator when the estimator says it dominates."""
    dag, tasks = _diamond_dag()
    a, b, c, d = tasks
    v5e = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-8')
    a100 = resources_lib.Resources(cloud='gcp', accelerators='A100:8')
    for t in tasks:
        t.set_resources({v5e, a100})
        t.set_time_estimator(
            lambda r: 600.0 if r.accelerators and
            'A100' in (r.accelerators or {}) else 6000.0)
    Optimizer.optimize(dag, minimize=OptimizeTarget.TIME, quiet=True)
    for t in tasks:
        assert 'A100' in t.best_resources.accelerators


def test_general_dag_local_search_path(enable_all_infra, monkeypatch):
    """Above the exact-search limit the coordinate-descent path must
    still converge to colocation (the same answer exact search gives)."""
    monkeypatch.setattr(optimizer_lib, '_EXACT_LIMIT', 1)
    test_general_dag_egress_prefers_colocation(enable_all_infra)
