"""RunPod cloud + GraphQL provisioner (cloud breadth).  The API sits
behind an injectable transport (provision/runpod/instance.py:
set_api_runner), so the pod lifecycle — deploy, ssh port-mapping
discovery, status map, terminate — runs without credentials or
network.  Model: tests/unit/test_lambda_cloud.py."""
from __future__ import annotations

import pytest

import skypilot_tpu as sky
from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import registry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.runpod import instance as runpod_instance


class FakeRunpodApi:
    """Minimal GraphQL account state machine."""

    def __init__(self):
        self.pods = {}     # id -> pod dict (myself{pods} shape)
        self.calls = []
        self._next = 0
        self.no_capacity = False

    def __call__(self, query, variables):
        self.calls.append((query, variables))
        if 'myself' in query and 'pods' in query:
            return 200, {'data': {'myself': {
                'pods': list(self.pods.values())}}}
        if 'podFindAndDeployOnDemand' in query:
            if self.no_capacity:
                return 200, {'errors': [
                    {'message': 'There are no longer any instances '
                                'available with the requested '
                                'specifications.'}]}
            inp = variables['input']
            pid = f'pod-{self._next:04d}'
            self._next += 1
            self.pods[pid] = {
                'id': pid,
                'name': inp['name'],
                'desiredStatus': 'RUNNING',
                'machine': {'podHostId': f'host{self._next}'},
                'runtime': {'ports': [
                    {'ip': f'194.1.0.{self._next}', 'isIpPublic': True,
                     'privatePort': 22, 'publicPort': 10022 + self._next},
                    {'ip': '10.4.0.9', 'isIpPublic': False,
                     'privatePort': 8000, 'publicPort': 8000},
                ]},
                '_input': inp,
            }
            return 200, {'data': {'podFindAndDeployOnDemand':
                                  {'id': pid, 'name': inp['name']}}}
        if 'podTerminate' in query:
            self.pods.pop(variables['input']['podId'], None)
            return 200, {'data': {'podTerminate': None}}
        return 404, {'errors': [{'message': f'unhandled: {query[:40]}'}]}


@pytest.fixture
def fake_api():
    api = FakeRunpodApi()
    runpod_instance.set_api_runner(api)
    yield api
    runpod_instance.set_api_runner(None)


def _config(cluster='rpc', itype='NVIDIA A100 80GB PCIe:1', count=1,
            ports=None):
    return provision_common.ProvisionConfig(
        provider_name='runpod', cluster_name=cluster, region='US',
        zones=[], deploy_vars={'instance_type': itype, 'disk_size': 64},
        count=count, ports_to_open=ports or [])


class TestProvisionLifecycle:

    def test_deploy_query_info_terminate(self, fake_api):
        record = runpod_instance.run_instances(_config(ports=[8000]))
        assert record.provider_name == 'runpod'
        assert len(record.created_instance_ids) == 1
        pod = next(iter(fake_api.pods.values()))
        assert pod['_input']['gpuTypeId'] == 'NVIDIA A100 80GB PCIe'
        assert pod['_input']['gpuCount'] == 1
        # Declared ports + ssh ride the creation call (launch-only).
        assert pod['_input']['ports'] == '22/tcp,8000/tcp'
        assert pod['_input']['env'][0]['key'] == 'PUBLIC_KEY'

        status = runpod_instance.query_instances('rpc')
        assert list(status.values())[0].value == 'UP'

        info = runpod_instance.get_cluster_info('rpc')
        assert info.ssh_user == 'root'
        # SSH goes through the proxy mapping for private port 22.
        assert info.instances[0].ssh_port > 10022
        assert info.instances[0].external_ip.startswith('194.')
        runners = runpod_instance.get_command_runners(info)
        assert runners[0].node[1] == info.instances[0].ssh_port

        runpod_instance.terminate_instances('rpc')
        assert runpod_instance.query_instances('rpc') == {}

    def test_idempotent_relaunch(self, fake_api):
        runpod_instance.run_instances(_config())
        record = runpod_instance.run_instances(_config())
        assert record.created_instance_ids == []
        assert len(fake_api.pods) == 1

    def test_community_tier_matches_catalog_prices(self, fake_api):
        """The optimizer priced COMMUNITY rates; deploying SECURE would
        bill above the cost decision."""
        runpod_instance.run_instances(_config())
        pod = next(iter(fake_api.pods.values()))
        assert pod['_input']['cloudType'] == 'COMMUNITY'

    def test_dead_pod_swept_and_redeployed(self, fake_api):
        """Pods persist after their container exits and cannot resume:
        relaunch must terminate the corpse and deploy fresh, not
        return it (review finding: 600s opaque hang)."""
        runpod_instance.run_instances(_config())
        old_id = next(iter(fake_api.pods))
        fake_api.pods[old_id]['desiredStatus'] = 'EXITED'
        record = runpod_instance.run_instances(_config())
        assert len(record.created_instance_ids) == 1
        assert record.created_instance_ids[0] != old_id
        assert old_id not in fake_api.pods

    def test_wait_fails_fast_on_dead_pod(self, fake_api):
        runpod_instance.run_instances(_config())
        pod = next(iter(fake_api.pods.values()))
        pod['desiredStatus'] = 'EXITED'
        import time
        start = time.time()
        with pytest.raises(exceptions.ProvisionError,
                           match='died while waiting'):
            runpod_instance.wait_instances('rpc')
        assert time.time() - start < 30

    def test_port_declaring_task_is_launchable(self):
        """OPEN_PORTS is satisfied at pod creation, so the provision-
        time feature check (slice_backend) must accept a port-declaring
        task on RunPod (review finding: the gate made the port wiring
        dead code — this asserts the exact gate path)."""
        rp = registry.CLOUD_REGISTRY['runpod']
        r = sky.Resources(cloud='runpod', accelerators='H100:1',
                          ports=[8000])
        feats = r.get_required_cloud_features()
        from skypilot_tpu.clouds import cloud as cloud_lib
        assert cloud_lib.CloudImplementationFeatures.OPEN_PORTS in feats
        rp.check_features_are_supported(r, feats)  # must not raise

    def test_multinode_rejected(self, fake_api):
        with pytest.raises(exceptions.ProvisionError,
                           match='single-node'):
            runpod_instance.run_instances(_config(count=2))

    def test_no_capacity_surfaces(self, fake_api):
        fake_api.no_capacity = True
        with pytest.raises(exceptions.ProvisionError,
                           match='no longer any instances'):
            runpod_instance.run_instances(_config())

    def test_stop_and_ports_rejected(self, fake_api):
        runpod_instance.run_instances(_config())
        with pytest.raises(exceptions.NotSupportedError):
            runpod_instance.stop_instances('rpc')
        with pytest.raises(exceptions.NotSupportedError):
            runpod_instance.open_ports('rpc', [9000])

    def test_status_map(self, fake_api):
        runpod_instance.run_instances(_config())
        pod = next(iter(fake_api.pods.values()))
        from skypilot_tpu.status_lib import ClusterStatus
        for api_status, want in [('RUNNING', ClusterStatus.UP),
                                 ('CREATED', ClusterStatus.INIT),
                                 ('EXITED', ClusterStatus.STOPPED),
                                 ('TERMINATED', None)]:
            pod['desiredStatus'] = api_status
            assert runpod_instance.query_instances('rpc') == {
                pod['id']: want}


class TestRunPodCloud:

    def test_feasibility_gpu_to_instance_type(self):
        rp = registry.CLOUD_REGISTRY['runpod']
        r = sky.Resources(cloud='runpod', accelerators='H100:1')
        launchable, _ = rp.get_feasible_launchable_resources(r)
        assert launchable
        assert launchable[0].instance_type == 'NVIDIA H100 PCIe:1'

    def test_tpu_spot_multinode_gated(self):
        from skypilot_tpu.clouds import cloud as cloud_lib
        rp = registry.CLOUD_REGISTRY['runpod']
        assert rp.get_feasible_launchable_resources(
            sky.Resources(accelerators='tpu-v5e-8'))[0] == []
        spot = sky.Resources(cloud='runpod', accelerators='H100:1',
                             capacity='spot')
        assert rp.get_feasible_launchable_resources(spot)[0] == []
        with pytest.raises(exceptions.NotSupportedError):
            rp.check_features_are_supported(
                sky.Resources(cloud='runpod'),
                {cloud_lib.CloudImplementationFeatures.MULTI_NODE})
        with pytest.raises(exceptions.NotSupportedError):
            rp.check_features_are_supported(
                sky.Resources(cloud='runpod'),
                {cloud_lib.CloudImplementationFeatures.STORAGE_MOUNTING})

    def test_pricing(self):
        assert catalog.get_hourly_cost(
            'runpod', 'NVIDIA A100 80GB PCIe:1') == pytest.approx(1.64)

    def test_api_key_in_header_not_url(self, monkeypatch):
        """The credential rides an Authorization: Bearer header — a key
        in the URL query string leaks through proxies/access logs."""
        import io
        import urllib.request as urlreq

        from skypilot_tpu.provision.runpod import instance as rp_inst

        captured = {}

        def fake_urlopen(req, timeout=None):
            captured['url'] = req.full_url
            captured['auth'] = req.get_header('Authorization')

            class _Resp(io.BytesIO):
                status = 200

                def __enter__(self):
                    return self

                def __exit__(self, *a):
                    return False

            return _Resp(b'{"data": {"myself": {"pods": []}}}')

        monkeypatch.setattr(urlreq, 'urlopen', fake_urlopen)
        monkeypatch.setattr(
            'skypilot_tpu.clouds.runpod.read_api_key',
            lambda: 'rk-secret')
        status, body = rp_inst._default_api_runner(  # pylint: disable=protected-access
            'query { myself { pods { id } } }', {})
        assert status == 200 and body['data']['myself']['pods'] == []
        assert 'rk-secret' not in captured['url']
        assert 'api_key' not in captured['url']
        assert captured['auth'] == 'Bearer rk-secret'

    def test_credentials_from_toml(self, tmp_path, monkeypatch):
        monkeypatch.setenv('HOME', str(tmp_path))
        monkeypatch.delenv('RUNPOD_API_KEY', raising=False)
        rp = registry.CLOUD_REGISTRY['runpod']
        ok, reason = rp.check_credentials()
        assert not ok and 'config.toml' in reason
        cfg = tmp_path / '.runpod'
        cfg.mkdir()
        (cfg / 'config.toml').write_text(
            '[default]\napi_key = "rk-abc123def"\n')
        ok, _ = rp.check_credentials()
        assert ok
        assert rp.get_current_user_identity() == ['runpod:rk-abc12']

    def test_cheapest_a100_pool_is_runpod(self, enable_all_infra):
        """RunPod's community A100 undercuts every other pool."""
        from skypilot_tpu import optimizer as optimizer_lib
        from skypilot_tpu.utils import dag_utils
        task = sky.Task(name='t', run='true')
        task.set_resources({
            sky.Resources(cloud=c, accelerators='A100-80GB:1')
            for c in ('azure', 'runpod')
        })
        dag = dag_utils.convert_entrypoint_to_dag(task)
        optimizer_lib.Optimizer.optimize(
            dag, minimize=optimizer_lib.OptimizeTarget.COST, quiet=True)
        assert str(task.best_resources.cloud).lower() == 'runpod'
