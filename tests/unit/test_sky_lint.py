"""Tier-1 gate: `sky lint` runs the full pass suite over the repo.

This is the CI surface of ISSUE 12's static-analysis plane: the whole
package is parsed once (AST-only — building the index imports nothing
from the analyzed tree), every pass runs, and the tree must be clean:
zero unsuppressed findings, every suppression carrying a reason, the
committed baseline either empty or exactly reproducing.  Bounded well
under the 30s budget (the full run is ~3s on CPU).
"""
from __future__ import annotations

import json
import pathlib
import sys
import time

import pytest

import skypilot_tpu
from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

_REPO = pathlib.Path(__file__).resolve().parents[2]


def _baseline_path() -> pathlib.Path:
    return _REPO / core.BASELINE_FILENAME


def test_lint_green_on_repo(lint_index):
    t0 = time.perf_counter()
    result = core.run_lint(lint_index,
                           baseline_path=_baseline_path())
    elapsed = time.perf_counter() - t0
    assert result.ok, (
        'sky lint found unsuppressed findings — fix them, or suppress '
        'inline with `# skytpu: lint-ok[rule] reason=...`:\n  ' +
        '\n  '.join(f.render() for f in result.findings))
    assert elapsed < 30, (
        f'full lint run took {elapsed:.1f}s (budget 30s) — a pass '
        f'went quadratic')


def test_every_suppression_carries_a_reason(lint_index):
    """Redundant with run_lint's suppression-invalid rule, but pinned
    separately: the reason-mandatory contract must survive framework
    refactors."""
    for rel, mod in lint_index.modules.items():
        for sup in mod.suppressions:
            assert sup.reason, (
                f'skypilot_tpu/{rel}:{sup.line}: lint-ok suppression '
                f'without reason=')


def test_index_build_is_ast_only():
    """Building an index must not import any analyzed module: a lint
    run cannot execute package code (and stays fast)."""
    before = set(sys.modules)
    index_lib.PackageIndex(
        pathlib.Path(skypilot_tpu.__file__).resolve().parent)
    imported = {m for m in set(sys.modules) - before
                if m.startswith('skypilot_tpu.') and
                not m.startswith('skypilot_tpu.analysis')}
    assert not imported, (
        f'index build imported analyzed modules: {sorted(imported)}')


def test_deterministic_json_output(lint_index):
    """Two runs over one tree are byte-identical (the --json report is
    diffable; no timestamps, stable ordering everywhere) — including
    across a freshly built index."""
    a = core.run_lint(lint_index,
                      baseline_path=_baseline_path()).to_json()
    b = core.run_lint(lint_index,
                      baseline_path=_baseline_path()).to_json()
    assert a == b
    fresh = index_lib.PackageIndex(
        pathlib.Path(skypilot_tpu.__file__).resolve().parent)
    c = core.run_lint(fresh, baseline_path=_baseline_path()).to_json()
    assert a == c
    payload = json.loads(a)
    assert payload['ok'] is True
    assert payload['version'] == 1


def test_stale_baseline_fails(lint_index, tmp_path):
    """A baselined finding that no longer reproduces is itself a
    finding: the baseline can only shrink."""
    stale = tmp_path / core.BASELINE_FILENAME
    stale.write_text(json.dumps({
        'version': 1,
        'findings': ['bare-print//cli_gone.py//bare print() long '
                     'since fixed'],
    }))
    result = core.run_lint(lint_index, baseline_path=stale)
    rules = {f.rule for f in result.findings}
    assert core.RULE_BASELINE_STALE in rules
    assert not result.ok


def test_committed_baseline_reproduces():
    """Every entry in the committed lint-baseline.json must still
    reproduce (enforced transitively by test_lint_green_on_repo, but
    this names the workflow: regenerate with
    `skytpu lint --update-baseline`)."""
    keys = core.load_baseline(_baseline_path())
    # The tree is currently clean; the baseline must be empty.  If a
    # future PR grandfathers findings, test_lint_green_on_repo keeps
    # them honest (stale entries fail).
    assert keys == [], (
        'lint-baseline.json has entries but the tree is expected '
        'clean — remove them or document why in the PR')


def test_unknown_rule_rejected(lint_index):
    with pytest.raises(ValueError, match='unknown rule'):
        core.run_lint(lint_index, rules=['no-such-rule'])


def test_rule_filter_runs_only_owning_passes(lint_index):
    result = core.run_lint(lint_index, rules=['facade-missing'])
    assert result.passes == ['facade-surface']
    assert result.ok


def test_cli_lint_json():
    """The `skytpu lint --json` surface: exit 0, parseable, ok."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(
        cli_mod.cli, ['lint', '--rule', 'facade-missing', '--json'])
    assert out.exit_code == 0, out.output
    payload = json.loads(out.output)
    assert payload['ok'] is True
    assert payload['passes'] == ['facade-surface']


# --------------------------------------------- ISSUE 13: protocol lint

def test_new_passes_registered():
    """The three distributed-protocol passes are in the default suite
    and own their documented rules."""
    catalog = core.rule_catalog()
    assert catalog['http-front-parity'] == 'http-contract'
    assert catalog['http-unknown-route'] == 'http-contract'
    assert catalog['http-raw-literal'] == 'http-contract'
    assert catalog['journal-unguarded-start'] == 'journal-protocol'
    assert catalog['journal-protocol-status'] == 'journal-protocol'
    assert catalog['mesh-unknown-axis'] == 'mesh-consistency'
    assert catalog['mesh-donated-reuse'] == 'mesh-consistency'


def test_replica_front_surfaces_identical(lint_index):
    """The threaded and async replica fronts expose byte-identical
    route surfaces and read the identical header set — proven from
    the ASTs, not sampled by HTTP tests.  This is the regression gate
    for every front-parity drift the http-contract pass can catch."""
    from skypilot_tpu.analysis.passes import http_contract

    res = http_contract._Resolver(lint_index)  # pylint: disable=protected-access
    threaded = http_contract.server_routes(
        lint_index, res, 'serve/model_server.py')
    asyncf = http_contract.server_routes(
        lint_index, res, 'serve/async_server.py')
    assert set(threaded) == set(asyncf)
    # The surface is the real one, not an empty-extraction artifact.
    assert {'/generate', '/generate_stream', '/generate_text',
            '/prefill_export', '/kv_import', '/drain',
            '/prefix_export', '/metrics', '/spans'} <= set(threaded)
    t_reads = http_contract.header_reads(
        lint_index, res, 'serve/model_server.py')
    a_reads = http_contract.header_reads(
        lint_index, res, 'serve/async_server.py')
    assert set(t_reads) == set(a_reads)
    assert 'X-SkyTPU-Deadline-Ms' in t_reads


def test_client_status_branches_covered(lint_index):
    """Every status code an in-package client equality-branches on is
    emittable by some server (regression gate for the 415 fix: the LB
    used to branch on a code no server could send)."""
    from skypilot_tpu.analysis.passes import http_contract

    res = http_contract._Resolver(lint_index)  # pylint: disable=protected-access
    emittable = http_contract.emitted_statuses(lint_index, res)
    for rel, line, code in http_contract.client_status_branches(
            lint_index):
        if 100 <= code < 600:
            assert code in emittable, (
                f'skypilot_tpu/{rel}:{line} branches on {code}, '
                f'which no server emits')


def test_protocol_table_shared_with_invariants():
    """chaos/invariants.py consumes the SAME paired-event table the
    journal-protocol pass verifies emit sites against — the lifecycle
    names and terminal statuses cannot drift apart."""
    from skypilot_tpu.chaos import invariants
    from skypilot_tpu.observability import event_protocol

    assert invariants._KV_HANDOFF is \
        event_protocol.BY_NAME['kv_handoff']  # pylint: disable=protected-access
    assert invariants._REPLICA_DRAIN is \
        event_protocol.BY_NAME['replica_drain']  # pylint: disable=protected-access
    assert invariants._QUEUED_WAIT.statuses == \
        ('granted', 'timeout', 'error')  # pylint: disable=protected-access


def test_protocol_table_parses_from_ast(lint_index):
    """The lint plane reads the protocol table from the AST (no
    imports); the parsed rows must match the runtime table exactly."""
    from skypilot_tpu.analysis.passes import journal_protocol
    from skypilot_tpu.observability import event_protocol

    parsed = {p.name: p for p in
              journal_protocol.load_protocol(lint_index)}
    assert set(parsed) == set(event_protocol.BY_NAME)
    for name, runtime in event_protocol.BY_NAME.items():
        ast_row = parsed[name]
        assert (ast_row.start, ast_row.end, ast_row.scope) == \
            (runtime.start, runtime.end, runtime.scope), name
        assert ast_row.statuses == runtime.statuses, name


def test_cli_lint_changed_flag():
    """`skytpu lint --changed` filters the report to git-changed files
    (full index, filtered findings); exits 0 on a clean tree."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(
        cli_mod.cli,
        ['lint', '--changed', '--rule', 'facade-missing', '--json'])
    assert out.exit_code == 0, out.output
    payload = json.loads(out.output)
    assert payload['ok'] is True
    out = runner.invoke(
        cli_mod.cli, ['lint', '--changed', '--update-baseline'])
    assert out.exit_code != 0  # mutually exclusive
