"""Tier-1 gate: `sky lint` runs the full pass suite over the repo.

This is the CI surface of ISSUE 12's static-analysis plane: the whole
package is parsed once (AST-only — building the index imports nothing
from the analyzed tree), every pass runs, and the tree must be clean:
zero unsuppressed findings, every suppression carrying a reason, the
committed baseline either empty or exactly reproducing.  Bounded well
under the 30s budget (the full run is ~3s on CPU).
"""
from __future__ import annotations

import json
import pathlib
import sys
import time

import pytest

import skypilot_tpu
from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

_REPO = pathlib.Path(__file__).resolve().parents[2]


def _baseline_path() -> pathlib.Path:
    return _REPO / core.BASELINE_FILENAME


def test_lint_green_on_repo(lint_index):
    t0 = time.perf_counter()
    result = core.run_lint(lint_index,
                           baseline_path=_baseline_path())
    elapsed = time.perf_counter() - t0
    assert result.ok, (
        'sky lint found unsuppressed findings — fix them, or suppress '
        'inline with `# skytpu: lint-ok[rule] reason=...`:\n  ' +
        '\n  '.join(f.render() for f in result.findings))
    assert elapsed < 30, (
        f'full lint run took {elapsed:.1f}s (budget 30s) — a pass '
        f'went quadratic')


def test_every_suppression_carries_a_reason(lint_index):
    """Redundant with run_lint's suppression-invalid rule, but pinned
    separately: the reason-mandatory contract must survive framework
    refactors."""
    for rel, mod in lint_index.modules.items():
        for sup in mod.suppressions:
            assert sup.reason, (
                f'skypilot_tpu/{rel}:{sup.line}: lint-ok suppression '
                f'without reason=')


def test_index_build_is_ast_only():
    """Building an index must not import any analyzed module: a lint
    run cannot execute package code (and stays fast)."""
    before = set(sys.modules)
    index_lib.PackageIndex(
        pathlib.Path(skypilot_tpu.__file__).resolve().parent)
    imported = {m for m in set(sys.modules) - before
                if m.startswith('skypilot_tpu.') and
                not m.startswith('skypilot_tpu.analysis')}
    assert not imported, (
        f'index build imported analyzed modules: {sorted(imported)}')


def test_deterministic_json_output(lint_index):
    """Two runs over one tree are byte-identical (the --json report is
    diffable; no timestamps, stable ordering everywhere) — including
    across a freshly built index."""
    a = core.run_lint(lint_index,
                      baseline_path=_baseline_path()).to_json()
    b = core.run_lint(lint_index,
                      baseline_path=_baseline_path()).to_json()
    assert a == b
    fresh = index_lib.PackageIndex(
        pathlib.Path(skypilot_tpu.__file__).resolve().parent)
    c = core.run_lint(fresh, baseline_path=_baseline_path()).to_json()
    assert a == c
    payload = json.loads(a)
    assert payload['ok'] is True
    assert payload['version'] == 1


def test_stale_baseline_fails(lint_index, tmp_path):
    """A baselined finding that no longer reproduces is itself a
    finding: the baseline can only shrink."""
    stale = tmp_path / core.BASELINE_FILENAME
    stale.write_text(json.dumps({
        'version': 1,
        'findings': ['bare-print//cli_gone.py//bare print() long '
                     'since fixed'],
    }))
    result = core.run_lint(lint_index, baseline_path=stale)
    rules = {f.rule for f in result.findings}
    assert core.RULE_BASELINE_STALE in rules
    assert not result.ok


def test_committed_baseline_reproduces():
    """Every entry in the committed lint-baseline.json must still
    reproduce (enforced transitively by test_lint_green_on_repo, but
    this names the workflow: regenerate with
    `skytpu lint --update-baseline`)."""
    keys = core.load_baseline(_baseline_path())
    # The tree is currently clean; the baseline must be empty.  If a
    # future PR grandfathers findings, test_lint_green_on_repo keeps
    # them honest (stale entries fail).
    assert keys == [], (
        'lint-baseline.json has entries but the tree is expected '
        'clean — remove them or document why in the PR')


def test_unknown_rule_rejected(lint_index):
    with pytest.raises(ValueError, match='unknown rule'):
        core.run_lint(lint_index, rules=['no-such-rule'])


def test_rule_filter_runs_only_owning_passes(lint_index):
    result = core.run_lint(lint_index, rules=['facade-missing'])
    assert result.passes == ['facade-surface']
    assert result.ok


def test_cli_lint_json():
    """The `skytpu lint --json` surface: exit 0, parseable, ok."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    out = runner.invoke(
        cli_mod.cli, ['lint', '--rule', 'facade-missing', '--json'])
    assert out.exit_code == 0, out.output
    payload = json.loads(out.output)
    assert payload['ok'] is True
    assert payload['passes'] == ['facade-surface']
