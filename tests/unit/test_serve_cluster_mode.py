"""Serve controller-on-cluster mode (VERDICT r2 missing #2).

The service daemon (controller + LB) runs on a provisioned controller
cluster — reference serve/core.py:203 behavior — instead of a local
process.  Hermetic: the controller cluster and the replica clusters it
launches all come from the local provisioner.
"""
from __future__ import annotations

import time

import pytest
import requests

import skypilot_tpu as sky
from skypilot_tpu import config as config_lib
from skypilot_tpu import global_user_state
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import constants as serve_constants
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec


@pytest.fixture(autouse=True)
def _cluster_mode(monkeypatch, _isolated_home):
    monkeypatch.setenv('SKYTPU_SERVE_SYNC_INTERVAL', '0.5')
    monkeypatch.setenv('SKYTPU_SERVE_PROBE_INTERVAL', '0.5')
    config_lib.set_nested(serve_constants.CONTROLLER_MODE_KEY, 'cluster')
    config_lib.set_nested(('serve', 'bucket'), 'local://serve-auto')
    global_user_state.set_enabled_clouds(['local'])
    yield
    config_lib.reload_config()


def _serve_task(name: str, replicas: int = 1) -> sky.Task:
    task = sky.Task(
        name=name,
        run='exec python3 -m http.server $SKYTPU_SERVE_REPLICA_PORT')
    task.set_resources(sky.Resources(cloud='local'))
    task.service = SkyServiceSpec.from_yaml_config({
        'readiness_probe': '/',
        'replica_policy': {'min_replicas': replicas,
                           'max_replicas': replicas},
    })
    return task


def _wait(predicate, timeout=120.0, gap=0.5):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(gap)
    return False


def test_serve_up_on_cluster_with_refill():
    """up -> controller cluster hosts the daemon -> replica serves ->
    replica eviction is refilled -> down cleans up."""
    name, endpoint = serve_core.up(_serve_task('csvc'), detach=True)
    assert name == 'csvc'

    # The controller cluster exists and hosts the daemon.
    record = global_user_state.get_cluster_from_name(
        serve_constants.CONTROLLER_CLUSTER_NAME)
    assert record is not None

    # Service reaches READY; the LB endpoint proxies to a replica.
    def ready():
        recs = serve_core.status(['csvc'])
        return recs and recs[0]['status'] == 'READY'
    assert _wait(ready), serve_core.status(['csvc'])

    def _serves():
        # The LB needs one sync cycle after READY to learn the replica.
        try:
            return requests.get(endpoint, timeout=10).status_code == 200
        except requests.RequestException:
            return False
    assert _wait(_serves, timeout=30)

    # Replica refill: tear the replica cluster down behind the
    # controller's back (slice eviction).
    replicas = serve_core.status(['csvc'])[0]['replicas']
    first = [r for r in replicas if r['status'] == 'READY'][0]
    sky.down(first['cluster_name'])

    def refilled():
        recs = serve_core.status(['csvc'])
        if not recs or recs[0]['status'] != 'READY':
            return False
        newer = [r for r in recs[0]['replicas']
                 if r['replica_id'] != first['replica_id'] and
                 r['status'] == 'READY']
        return bool(newer)
    assert _wait(refilled), serve_core.status(['csvc'])
    assert _wait(_serves, timeout=30)

    # Down removes the service and its replicas (controller cluster
    # itself stays, like the reference's shared controller VM).
    serve_core.down('csvc')
    assert _wait(lambda: not serve_core.status(['csvc']))


def test_status_empty_without_controller():
    assert serve_core.status() == []
    with pytest.raises(Exception):
        serve_core.down('nosuch')
