"""Cluster status reconciliation drift matrix + per-cluster locking.

VERDICT round-1 item 4: the cloud-API view is necessary but not
sufficient — an UP record must survive a skylet liveness probe; every
drift case (UP-but-dead-skylet, STOPPED-but-running, partial slice,
vanished) must land in the right state.  Parity:
/root/reference/sky/backends/backend_utils.py:1669 and the per-cluster
FileLock at cloud_vm_ray_backend.py:2729-2731.
"""
from __future__ import annotations

import threading
import time

import filelock
import pytest

from skypilot_tpu import global_user_state
from skypilot_tpu import status_lib
from skypilot_tpu.backends import backend_utils

UP = status_lib.ClusterStatus.UP
INIT = status_lib.ClusterStatus.INIT
STOPPED = status_lib.ClusterStatus.STOPPED
WAITING = status_lib.ClusterStatus.WAITING


class _FakeRunner:

    def __init__(self, rc: int):
        self._rc = rc

    def run(self, cmd, **kwargs):
        del cmd, kwargs
        return self._rc


class _FakeHandle:
    """Minimal picklable stand-in for SliceResourceHandle."""
    provider_name = 'local'
    launched_resources = None
    launched_nodes = 1

    def __init__(self, cluster_name: str, probe_rc: int = 0):
        self.cluster_name = cluster_name
        self.probe_rc = probe_rc

    def get_command_runners(self):
        return [_FakeRunner(self.probe_rc)]


def _record_cluster(name: str, status, probe_rc: int = 0) -> None:
    handle = _FakeHandle(name, probe_rc)
    global_user_state.add_or_update_cluster(name, handle,
                                            requested_resources=None,
                                            ready=True)
    global_user_state.set_cluster_status(name, status)


def _set_cloud_view(monkeypatch, statuses):
    monkeypatch.setattr(
        'skypilot_tpu.provision.query_instances',
        lambda provider, cluster, **kw: dict(statuses))


class TestDriftMatrix:

    def test_up_healthy_skylet_stays_up(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=0)
        _set_cloud_view(monkeypatch, {'h0': UP, 'h1': UP})
        assert backend_utils.refresh_cluster_status('c') == UP

    def test_up_but_dead_skylet_degrades_to_init(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=1)
        _set_cloud_view(monkeypatch, {'h0': UP, 'h1': UP})
        assert backend_utils.refresh_cluster_status('c') == INIT
        assert global_user_state.get_cluster_from_name(
            'c')['status'] == INIT

    def test_up_probe_skipped_when_disabled(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=1)
        _set_cloud_view(monkeypatch, {'h0': UP})
        assert backend_utils.refresh_cluster_status(
            'c', probe_runtime=False) == UP

    def test_stopped_but_running_degrades_to_init(self, monkeypatch):
        _record_cluster('c', STOPPED)
        _set_cloud_view(monkeypatch, {'h0': UP, 'h1': UP})
        assert backend_utils.refresh_cluster_status('c') == INIT

    def test_waiting_granted_becomes_init(self, monkeypatch):
        _record_cluster('c', WAITING)
        _set_cloud_view(monkeypatch, {'h0': UP})
        assert backend_utils.refresh_cluster_status('c') == INIT

    def test_up_record_all_stopped_cloud(self, monkeypatch):
        _record_cluster('c', UP)
        _set_cloud_view(monkeypatch, {'h0': STOPPED, 'h1': STOPPED})
        assert backend_utils.refresh_cluster_status('c') == STOPPED

    def test_partial_slice_degrades_to_init(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=0)
        _set_cloud_view(monkeypatch, {'h0': UP, 'h1': STOPPED})
        assert backend_utils.refresh_cluster_status('c') == INIT

    def test_partially_vanished_slice_degrades_to_init(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=0)
        _set_cloud_view(monkeypatch, {'h0': UP, 'h1': None})
        assert backend_utils.refresh_cluster_status('c') == INIT

    def test_vanished_cluster_removed(self, monkeypatch):
        _record_cluster('c', UP)
        _set_cloud_view(monkeypatch, {'h0': None, 'h1': None})
        assert backend_utils.refresh_cluster_status('c') is None
        assert global_user_state.get_cluster_from_name('c') is None

    def test_no_trace_removed(self, monkeypatch):
        _record_cluster('c', UP)
        _set_cloud_view(monkeypatch, {})
        assert backend_utils.refresh_cluster_status('c') is None

    def test_query_failure_keeps_cached_status(self, monkeypatch):
        _record_cluster('c', UP, probe_rc=0)

        def boom(provider, cluster, **kw):
            raise RuntimeError('cloud API down')

        monkeypatch.setattr('skypilot_tpu.provision.query_instances', boom)
        assert backend_utils.refresh_cluster_status('c') == UP


class TestProbeSkylet:

    def test_probe_alive(self):
        assert backend_utils.probe_skylet(_FakeHandle('c', probe_rc=0))

    def test_probe_dead(self):
        assert not backend_utils.probe_skylet(_FakeHandle('c', probe_rc=1))

    def test_probe_ssh_error(self):
        class _Boom(_FakeHandle):

            def get_command_runners(self):
                raise ConnectionError('ssh down')

        assert not backend_utils.probe_skylet(_Boom('c'))


class TestClusterLock:

    def test_lock_is_exclusive(self):
        acquired = threading.Event()
        release = threading.Event()

        def holder():
            with backend_utils.cluster_file_lock('lk'):
                acquired.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        assert acquired.wait(5)
        with pytest.raises(filelock.Timeout):
            with backend_utils.cluster_file_lock('lk', timeout=0.2):
                pass
        release.set()
        t.join()
        # Released: now acquirable.
        with backend_utils.cluster_file_lock('lk', timeout=1):
            pass

    def test_refresh_returns_cached_when_lock_busy(self, monkeypatch):
        monkeypatch.setattr(backend_utils,
                            '_STATUS_LOCK_TIMEOUT_SECONDS', 0.2)
        _record_cluster('c', STOPPED)
        # Cloud says UP, but the lock is held: refresh must not block
        # or mutate — it returns the cached STOPPED.
        _set_cloud_view(monkeypatch, {'h0': UP})
        acquired = threading.Event()
        release = threading.Event()

        def holder():
            with backend_utils.cluster_file_lock('c'):
                acquired.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        assert acquired.wait(5)
        t0 = time.time()
        assert backend_utils.refresh_cluster_status('c') == STOPPED
        assert time.time() - t0 < 3
        release.set()
        t.join()
        assert global_user_state.get_cluster_from_name(
            'c')['status'] == STOPPED
