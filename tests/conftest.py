"""Shared test fixtures.

Test strategy mirrors SURVEY.md §4: unit tests are hermetic (temp
SKYTPU_HOME, no cloud access); compute tests run on a virtual 8-device CPU
mesh (`xla_force_host_platform_device_count`) so multi-chip sharding is
exercised without TPU hardware.
"""
from __future__ import annotations

import os
import sys

# Tests must be hermetic and fast: always virtual CPU devices, never the
# tunnelled TPU.  The environment may pre-register a remote-compile PJRT
# plugin at *interpreter start* (sitecustomize keyed off
# PALLAS_AXON_POOL_IPS), which routes even CPU compiles through the TPU
# relay — too late to undo from here.  Re-exec once with a clean env so
# the interpreter starts without the plugin.
def pytest_configure(config):
    if os.environ.get('SKYTPU_TPU_TESTS') == '1':
        # Hardware mode: run against the real TPU (tests/tpu smoke
        # suite).  Interpret mode must never green-light a kernel that
        # won't lower, so the real chip is the point here.
        return
    if not os.environ.get('PALLAS_AXON_POOL_IPS'):
        return
    # Restore the real stdout/stderr fds before exec'ing, else all
    # output of the re-exec'd run lands in the dead capture tempfile.
    capman = config.pluginmanager.getplugin('capturemanager')
    if capman is not None:
        capman.stop_global_capturing()
    env = {k: v for k, v in os.environ.items()
           if k != 'PALLAS_AXON_POOL_IPS'}
    env['JAX_PLATFORMS'] = 'cpu'
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    os.execve(sys.executable,
              [sys.executable, '-m', 'pytest'] + sys.argv[1:], env)


if os.environ.get('SKYTPU_TPU_TESTS') != '1':
    os.environ['JAX_PLATFORMS'] = 'cpu'
    _flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in _flags:
        os.environ['XLA_FLAGS'] = (
            _flags + ' --xla_force_host_platform_device_count=8').strip()

import time as _time  # noqa: E402

import pytest  # noqa: E402

# ----------------------------------------------------- tier-1 time budget
# The tier-1 verify command hard-kills the suite at 870s (`timeout -k 10
# 870`).  A suite that finishes at 860s is one flaky rerun away from a
# kill with NO failure attribution — so when a full tier-1 run crosses
# the trip fraction of the budget, this guard FAILS the run explicitly
# and names the top-10 slowest tests (the ones to slow-mark or speed
# up).  Partial dev runs (< _TIER1_MIN_ITEMS collected tests) never
# trip.

_TIER1_BUDGET_ENV = 'SKYTPU_TIER1_WALLCLOCK_BUDGET_S'
_TIER1_DEFAULT_BUDGET_S = 870.0
_TIER1_TRIP_FRACTION = 0.92
_TIER1_MIN_ITEMS = 400

_session_t0 = _time.monotonic()
_test_durations = {}


def tier1_wallclock_violation(elapsed_s, n_items, durations,
                              budget_s=_TIER1_DEFAULT_BUDGET_S,
                              trip_fraction=_TIER1_TRIP_FRACTION,
                              min_items=_TIER1_MIN_ITEMS):
    """Pure guard logic (unit-tested in test_wallclock_guard.py):
    returns the failure report string, or None when within budget or
    not a full-suite run."""
    if n_items < min_items:
        return None
    trip_s = budget_s * trip_fraction
    if elapsed_s <= trip_s:
        return None
    slowest = sorted(durations.items(), key=lambda kv: -kv[1])[:10]
    lines = [
        f'tier-1 wall clock {elapsed_s:.0f}s exceeded the guard '
        f'threshold {trip_s:.0f}s ({trip_fraction:.0%} of the '
        f'{budget_s:.0f}s timeout budget) — slow-mark or speed up the '
        f'worst offenders before the hard timeout starts killing CI '
        f'runs with no attribution.',
        'Top 10 slowest tests:',
    ]
    lines += [f'  {dur:8.1f}s  {nodeid}' for nodeid, dur in slowest]
    return '\n'.join(lines)


def pytest_sessionstart(session):
    del session
    global _session_t0
    _session_t0 = _time.monotonic()


def pytest_runtest_logreport(report):
    if report.when == 'call':
        _test_durations[report.nodeid] = report.duration


@pytest.hookimpl(hookwrapper=True)
def pytest_runtestloop(session):
    yield
    budget = float(os.environ.get(_TIER1_BUDGET_ENV,
                                  _TIER1_DEFAULT_BUDGET_S))
    message = tier1_wallclock_violation(
        _time.monotonic() - _session_t0, len(session.items),
        _test_durations, budget_s=budget)
    if message is not None:
        import sys as _sys
        print(f'\nFAILED (wall-clock guard)\n{message}',
              file=_sys.stderr)
        session.testsfailed += 1


def _reap_daemons(home: str) -> None:
    """Kill every daemon a test spawned under its SKYTPU_HOME.

    Local-provisioner 'hosts' live under the home dir; deleting the tmp
    dir without this sweep orphans their skylets/job supervisors (five
    such orphans were found after the round-1 test runs).  Two passes:
    (1) pid files written under the home, (2) any process whose cmdline
    or cwd references the home (controllers, LBs, tail loops).
    """
    import psutil

    def _kill_tree(pid: int) -> None:
        try:
            proc = psutil.Process(pid)
        except psutil.NoSuchProcess:
            return
        procs = [proc]
        try:
            procs += proc.children(recursive=True)
        except psutil.NoSuchProcess:
            pass
        for p in procs:
            try:
                p.kill()
            except psutil.NoSuchProcess:
                pass

    # os.walk (not glob) so pid files under dot-dirs like .skytpu are
    # found too.
    for dirpath, _, filenames in os.walk(home):
        for fname in filenames:
            if not fname.endswith('.pid'):
                continue
            try:
                with open(os.path.join(dirpath, fname),
                          encoding='utf-8') as f:
                    _kill_tree(int(f.read().strip()))
            except (OSError, ValueError):
                pass
    me = os.getpid()
    for proc in psutil.process_iter(['pid', 'cmdline', 'cwd']):
        if proc.info['pid'] == me:
            continue
        try:
            cmdline = ' '.join(proc.info['cmdline'] or ())
            cwd = proc.info['cwd'] or ''
        except (psutil.NoSuchProcess, psutil.AccessDenied,
                psutil.ZombieProcess):
            continue
        if home in cmdline or cwd.startswith(home):
            _kill_tree(proc.info['pid'])


def _skylet_pids() -> set:
    import psutil
    pids = set()
    for proc in psutil.process_iter(['pid', 'cmdline']):
        try:
            cmdline = ' '.join(proc.info['cmdline'] or ())
        except (psutil.NoSuchProcess, psutil.AccessDenied,
                psutil.ZombieProcess):
            continue
        if 'skypilot_tpu.skylet' in cmdline:
            pids.add(proc.info['pid'])
    return pids


@pytest.fixture(scope='session', autouse=True)
def _daemon_registry_env(tmp_path_factory):
    """Session-scoped spawn registry OUTSIDE per-test homes.

    Every daemon spawn records itself here (utils/daemon_registry); at
    session start we reap strays from crash-interrupted PREVIOUS runs —
    their registry is the default real-home path, so check that one too.
    """
    from skypilot_tpu.utils import daemon_registry
    # First: reap orphans left by earlier (possibly kill -9'd) runs,
    # recorded in the default registry.
    daemon_registry.reap_stale()
    # Then isolate this session's spawns in a session-local registry.
    path = str(tmp_path_factory.mktemp('daemon_registry') / 'reg.jsonl')
    os.environ['SKYTPU_DAEMON_REGISTRY'] = path
    yield path
    # Kill anything still alive that this session spawned.
    for rec in daemon_registry._load():  # pylint: disable=protected-access
        if daemon_registry._same_process(rec):  # pylint: disable=protected-access
            daemon_registry._kill_tree(rec['pid'])  # pylint: disable=protected-access
    os.environ.pop('SKYTPU_DAEMON_REGISTRY', None)


@pytest.fixture(scope='session', autouse=True)
def _no_skylet_orphans():
    """Hard guarantee: a pytest run leaves zero NEW skylet daemons
    behind, whatever path spawned them (VERDICT round-1 item 7)."""
    import psutil
    before = _skylet_pids()
    yield
    for pid in _skylet_pids() - before:
        try:
            psutil.Process(pid).kill()
        except psutil.NoSuchProcess:
            pass


@pytest.fixture(autouse=True)
def _isolated_home(tmp_path, monkeypatch):
    """Every test gets a fresh SKYTPU_HOME (state.db, config, jobs.db);
    daemons spawned under it are reaped at teardown."""
    home = tmp_path / 'skytpu_home'
    home.mkdir()
    monkeypatch.setenv('SKYTPU_HOME', str(home))
    monkeypatch.setenv('SKYTPU_JOB_DB', str(home / 'jobs.db'))
    monkeypatch.delenv('SKYTPU_CONFIG', raising=False)
    from skypilot_tpu import config as config_mod
    from skypilot_tpu.catalog import common as catalog_common
    config_mod.reload_config()
    # Catalog loads are lru-cached; a prior test's `catalog refresh`
    # (user catalog under ITS home) must not leak rows into this one.
    catalog_common.clear_catalog_caches()
    yield home
    _reap_daemons(str(home))
    config_mod.reload_config()
    catalog_common.clear_catalog_caches()


@pytest.fixture
def enable_all_infra(monkeypatch):
    """Pretend every infra has credentials (parity: reference
    tests/common.py enable_all_clouds), so optimizer/catalog tests run
    offline."""
    from skypilot_tpu import global_user_state
    from skypilot_tpu.clouds import registry
    global_user_state.set_enabled_clouds(list(registry.CLOUD_REGISTRY.keys()))
    for cloud in registry.CLOUD_REGISTRY.values():
        monkeypatch.setattr(type(cloud), 'check_credentials',
                            lambda self: (True, None))
    yield


# --------------------------------------------------------------- slow tier
# Measured tiering (VERDICT r3 item 6 / r4 item 5): tests >= ~5s wall on
# the CI box carry @pytest.mark.slow, so the default dev loop is
# `pytest tests/unit -m 'not slow'` (< 5 min) while CI runs everything.
# Maintained here centrally (one table, re-measured with --durations)
# instead of scattering decorators across files; match is by
# (file basename, test name prefix) so parametrized ids stay covered.

_SLOW_TESTS = {
    'test_batching_engine.py': (
        'test_single_request_matches_decode',
        'test_concurrent_requests_exact', 'test_moe_config_exact'),
    'test_benchmark.py': ('test_launch_collect_score',),
    'test_callbacks.py': ('test_keras_callback_gated',),
    'test_cli.py': ('test_launch_status_queue_logs_down',
                    'test_down_glob'),
    'test_compute.py': ('test_forward_shape', 'test_scan_matches_unrolled',
                        'test_remat_policy_and_logits_dtype_parity',
                        'test_sharded_train_step_loss_matches_single',
                        'test_grad_matches', 'test_matches_reference',
                        'test_gqa_matches_reference',
                        'test_model_sequence_parallel_ulysses',
                        'test_pipeline_sp_ulysses_gqa'),
    'test_controller_utils.py': ('test_job_reads_translated_mounts',),
    'test_decode.py': ('test_greedy_generation_parity',
                       'test_moe_greedy_generation_parity',
                       'test_family_variants_generation_parity',
                       'test_prefill_logits_match_full_forward',
                       'test_batched_step_matches_per_sequence_decode',
                       'test_multi_step_generation_parity'),
    'test_chaos.py': ('test_elastic_expand_round_trip',
                      'test_replica_rank_death_full_rebuild'),
    'test_distributed_bootstrap.py': (
        'test_two_process_bootstrap_and_psum',),
    'test_elastic.py': (
        'test_shrink_expand_round_trip_with_loss_continuity',),
    'test_flash_kernels.py': ('test_pallas_backward_bf16',
                              'test_pallas_backward_matches_reference',
                              'test_ring_attention_uses_pallas_kernels'),
    'test_gang_distributed_e2e.py': (
        'test_gang_task_runs_distributed_psum',),
    'test_import_weights.py': ('test_finetune_init_from_converted',),
    'test_launch_e2e.py': ('test_exec_reuses_cluster_and_queue',
                           'test_stop_start_cycle'),
    'test_managed_jobs.py': ('test_launch_detached_process_mode',
                             'test_cancel_terminal_job_noop',
                             'test_preemption_recovery'),
    'test_model_server.py': ('test_',),   # module: shared jit fixture
    'test_async_server.py': ('test_',),   # module: shared jit fixture
    'test_pipeline.py': ('test_pipeline_',),
    'test_quantize.py': ('test_generation_close_to_fp',
                         'test_moe_experts_quantized_router_not',
                         'test_tied_embeddings_not_quantized_path'),
    'test_serve_cluster_mode.py': ('test_',),
    'test_serve_real_checkpoint.py': ('test_',),
    'test_slice_replica.py': ('test_two_host_through_lb',
                              'test_four_host_through_lb'),
    'test_usage.py': ('test_exec_records_separately',),
    'test_stress.py': ('test_',),
}


def pytest_collection_modifyitems(config, items):
    del config
    for item in items:
        prefixes = _SLOW_TESTS.get(item.path.name)
        if prefixes and item.name.startswith(prefixes):
            item.add_marker(pytest.mark.slow)


# ------------------------------------------------------- sky lint index
# One parse of the whole package shared by every lint-plane test
# (test_sky_lint + the three migrated lint wrappers): the index is
# immutable, so session scope is safe and saves ~1s per consumer.
@pytest.fixture(scope='session')
def lint_index():
    import pathlib

    import skypilot_tpu
    from skypilot_tpu.analysis import index as index_lib
    return index_lib.PackageIndex(
        pathlib.Path(skypilot_tpu.__file__).resolve().parent)
