"""Shared test fixtures.

Test strategy mirrors SURVEY.md §4: unit tests are hermetic (temp
SKYTPU_HOME, no cloud access); compute tests run on a virtual 8-device CPU
mesh (`xla_force_host_platform_device_count`) so multi-chip sharding is
exercised without TPU hardware.
"""
from __future__ import annotations

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
_flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_home(tmp_path, monkeypatch):
    """Every test gets a fresh SKYTPU_HOME (state.db, config, jobs.db)."""
    home = tmp_path / 'skytpu_home'
    home.mkdir()
    monkeypatch.setenv('SKYTPU_HOME', str(home))
    monkeypatch.setenv('SKYTPU_JOB_DB', str(home / 'jobs.db'))
    monkeypatch.delenv('SKYTPU_CONFIG', raising=False)
    from skypilot_tpu import config as config_mod
    config_mod.reload_config()
    yield home
    config_mod.reload_config()


@pytest.fixture
def enable_all_infra(monkeypatch):
    """Pretend every infra has credentials (parity: reference
    tests/common.py enable_all_clouds), so optimizer/catalog tests run
    offline."""
    from skypilot_tpu import global_user_state
    from skypilot_tpu.clouds import registry
    global_user_state.set_enabled_clouds(list(registry.CLOUD_REGISTRY.keys()))
    for cloud in registry.CLOUD_REGISTRY.values():
        monkeypatch.setattr(type(cloud), 'check_credentials',
                            lambda self: (True, None))
    yield
