"""Serving benchmark: the decode hot loop under open-loop load.

Drives `serve.batching_engine.ContinuousBatchingEngine` directly (no
HTTP in the way) with Poisson arrivals over mixed prompt lengths and
reports the numbers a serving SLO is written in:

- decode tokens/s        (aggregate, across all in-flight requests)
- TTFT p50/p99           (submit -> first token)
- ITL  p50/p99           (gap between consecutive tokens of a request)
- speedup vs the pre-pipeline engine (`pipelined=False`: inline
  full-prompt prefill + one host sync per generated token) on the SAME
  workload — the A/B for the on-device-sampling + pipelined-tick loop.
- chunked-prefill stall probe: while `slots-1` decodes run, admit one
  LONG prompt and measure the worst ITL the running requests suffer;
  with chunked prefill that stall is bounded by ONE chunk's compute
  (reported alongside the unchunked stall for contrast).
- paged-KV capacity probe: at a FIXED cache-memory budget (what the
  dense `[L, slots, h_kv, max_len, d]` cache occupies), size an
  int8-paged pool with the same bytes and run that many requests
  CONCURRENTLY — max concurrent slots at fixed memory is the number
  the paged cache exists to move (dense reserves max_len per slot;
  pages reserve only what a request can touch).
- prefix-cache TTFT probe: a shared system prompt is prefilled cold
  once, then re-requested — the hit adopts the cached pages and
  prefills only the tail chunk, so TTFT collapses (reported as
  hit/cold ratio, with the hit's `prefix_hit_pages` from its span).
- disaggregation A/B: the SAME bursty workload (steady chat SSE
  streams + Poisson long-prompt bursts) through the real routing LB
  over HTTP against two replica fleets — role-blind mixed vs
  prefill+decode with KV page handoff.  The pinned number is the
  chat ITL p99 ratio during bursts (disaggregated / mixed): keeping
  long prefills off decode replicas is THE tail-latency lever under
  mixed traffic, and the handed-off pages land the decode-side
  admission as a prefix hit.
- self-speculative decoding A/B: the SAME repetitive-text workload
  (periodic prompts — greedy decode on the tiny model locks into
  cycles, the regime prompt-lookup drafting exists for) with
  `spec_tokens=0` vs `spec_tokens=3`.  The pinned numbers are the
  ITL p50 speedup (one verify tick emits every accepted token, so
  accepted tokens arrive with near-zero gaps) and the mean
  acceptance length from engine stats; greedy outputs must be
  byte-identical across the two runs (token-exactness is the
  contract, speed is the only variable).
- paged decode-kernel A/B: the same paged int8 workload under
  `SKYTPU_DECODE_KERNEL=gather` (XLA gather reference) vs `pallas`
  (block-table-indexed in-kernel page reads).  Off-TPU the Pallas
  path runs under the interpreter (`SKYTPU_PALLAS_INTERPRET=1`), so
  the section asserts PARITY and presence only — interpret-mode
  wall-clock is not a perf claim; on a TPU backend the same section
  reads out the fused kernel's tokens/s against the gather path.
- --smoke also scrapes `/metrics` (observability/metrics.py exposition
  served on a loopback port) before, during, and after the pipelined
  run, asserts the key engine series are present and monotone (ticks,
  decode tokens), and writes the samples into the JSON — the perf
  trajectory carries an observability signal per change.

Prints ONE JSON line and writes it to --out (BENCH_serve.json;
--smoke uses a seconds-scale config and BENCH_serve_smoke.json — the
tier-1 perf smoke `tests/unit/test_bench_serve.py` runs).

On a TPU replica this measures the serving half of $/token; on CPU
(tiny config) it is a functional perf smoke — the pipelined win there
comes from removing the per-token host sync + per-slot eager staging,
which is also the mechanism that matters on real hardware.
"""
from __future__ import annotations

import argparse
import json
import threading
import time
from typing import Any, Dict, List, Optional


def _percentile(values: List[float], pct: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    idx = min(len(values) - 1, int(round(pct / 100.0 * (len(values) - 1))))
    return values[idx]


class _Tracked:
    """One benchmark request: submit time + per-token arrival times."""

    def __init__(self, prompt: List[int], max_new: int) -> None:
        self.prompt = prompt
        self.max_new = max_new
        self.submit_t: float = 0.0
        self.token_times: List[float] = []
        self.handle = None

    def watcher(self, token: Optional[int]) -> None:
        if token is not None:
            self.token_times.append(time.perf_counter())

    @property
    def ttft(self) -> Optional[float]:
        if not self.token_times:
            return None
        return self.token_times[0] - self.submit_t

    @property
    def itls(self) -> List[float]:
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]


def _workload(rng, n_requests: int, rate: float, prompt_lens: List[int],
              max_new: int, vocab: int) -> List[Any]:
    """[(arrival_offset_s, _Tracked)] — Poisson arrivals, prompt length
    cycling through the mix with +-25% jitter."""
    out = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        base = prompt_lens[i % len(prompt_lens)]
        n = max(1, int(base * (0.75 + 0.5 * rng.random())))
        prompt = [int(x) for x in rng.integers(1, vocab - 1, size=n)]
        out.append((t, _Tracked(prompt, max_new)))
    return out


def _run_load(engine, workload) -> Dict[str, Any]:
    """Submit the workload open-loop; wait for every request."""
    t0 = time.perf_counter()

    def submitter():
        for offset, tracked in workload:
            now = time.perf_counter() - t0
            if offset > now:
                time.sleep(offset - now)
            tracked.submit_t = time.perf_counter()
            tracked.handle = engine.submit(tracked.prompt,
                                           tracked.max_new)
            tracked.handle.add_watcher(tracked.watcher)

    thread = threading.Thread(target=submitter)
    thread.start()
    thread.join()
    for _, tracked in workload:
        tracked.handle.result(timeout=600)
    tokens = sum(len(t.token_times) for _, t in workload)
    last = max(t.token_times[-1] for _, t in workload if t.token_times)
    first = min(t.submit_t for _, t in workload)
    span = max(last - first, 1e-9)
    ttfts = [t.ttft for _, t in workload if t.ttft is not None]
    itls = [g for _, t in workload for g in t.itls]
    return {
        'requests': len(workload),
        'tokens': tokens,
        'tokens_per_s': round(tokens / span, 2),
        'ttft_p50_ms': round(_percentile(ttfts, 50) * 1e3, 2),
        'ttft_p99_ms': round(_percentile(ttfts, 99) * 1e3, 2),
        'itl_p50_ms': round(_percentile(itls, 50) * 1e3, 2),
        'itl_p99_ms': round(_percentile(itls, 99) * 1e3, 2),
    }


def _scrape_metrics(port: int) -> Dict[str, Any]:
    """One /metrics scrape over real HTTP -> the counter values the
    smoke asserts on (summed across label sets)."""
    import urllib.request

    from skypilot_tpu.observability import metrics as metrics_lib
    with urllib.request.urlopen(
            f'http://127.0.0.1:{port}/metrics', timeout=10) as resp:
        text = resp.read().decode()
    parsed = metrics_lib.parse_exposition(text)

    def total(name: str) -> float:
        return sum((parsed.get(name) or {}).values())

    return {
        'ticks': total('skytpu_engine_ticks_total'),
        'decode_tokens': total('skytpu_engine_decode_tokens_total'),
        'queue_wait_count':
            total('skytpu_engine_queue_wait_seconds_count'),
        'itl_count': total('skytpu_engine_itl_seconds_count'),
        'histograms_present': all(
            f'skytpu_engine_{h}_seconds_bucket' in parsed
            for h in ('queue_wait', 'itl', 'ttft')),
    }


def _measure_chunk_compute(cfg, params, chunk: int, max_len: int,
                           vocab: int) -> float:
    """Median wall time of ONE jitted prefill-chunk continuation (the
    unit the chunked-prefill stall bound is stated in)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import decode
    fn = jax.jit(lambda p, t, c: decode.prefill_chunk(cfg, p, t, c))
    _, cache = decode.prefill(
        cfg, params, jnp.ones((1, chunk), jnp.int32), max_len=max_len)
    piece = jnp.ones((1, chunk), jnp.int32) % (vocab - 1) + 1
    logits, _ = fn(params, piece, cache)   # compile
    logits.block_until_ready()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        logits, new_cache = fn(params, piece, cache)
        logits.block_until_ready()
        del new_cache
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _stall_probe(cfg, params, *, slots: int, prompt_len: int,
                 chunk: int, max_new_bg: int, vocab: int,
                 pipelined_chunked: bool) -> Dict[str, Any]:
    """Admit a long prompt while slots-1 decodes run; the worst ITL the
    running decodes see during the admission window IS the head-of-line
    stall that admission imposed."""
    import numpy as np

    from skypilot_tpu.serve import batching_engine
    max_len = prompt_len + 2 * max_new_bg + 16
    eng = batching_engine.ContinuousBatchingEngine(
        cfg, params, max_len=max_len, slots=slots,
        prefill_chunk=chunk if pipelined_chunked else max(prompt_len, 16))
    try:
        # Warm every compile on the admission path (tick, the long
        # prompt's chunk-0 bucket, the chunk continuation, insert) so
        # the probe measures the steady-state stall, not XLA.
        eng.generate([1, 2, 3], 2, timeout=600)
        eng.generate(list(range(1, prompt_len + 1)), 2, timeout=600)
        rng = np.random.default_rng(0)
        background = []
        for _ in range(max(1, slots - 1)):
            tracked = _Tracked(
                [int(x) for x in rng.integers(1, vocab - 1, size=8)],
                max_new_bg)
            tracked.submit_t = time.perf_counter()
            tracked.handle = eng.submit(tracked.prompt, tracked.max_new)
            tracked.handle.add_watcher(tracked.watcher)
            background.append(tracked)
        # Steady decode before the admission hits.
        deadline = time.time() + 120
        while (min(len(t.token_times) for t in background) < 5 and
               time.time() < deadline):
            time.sleep(0.005)
        long_prompt = [int(x)
                       for x in rng.integers(1, vocab - 1,
                                             size=prompt_len)]
        t_admit = time.perf_counter()
        handle = eng.submit(long_prompt, 2)
        handle.result(timeout=600)
        t_first = time.perf_counter()
        for t in background:
            t.handle.cancel()
        # Worst gap any running decode saw inside the admission window.
        stall = 0.0
        for t in background:
            times = [x for x in t.token_times
                     if t_admit - 0.5 <= x <= t_first + 0.5]
            stall = max(stall, max(
                (b - a for a, b in zip(times, times[1:])), default=0.0))
        baseline_itls = [g for t in background for g in t.itls
                         if g > 0]
        return {
            'max_itl_during_admission_ms': round(stall * 1e3, 2),
            'baseline_itl_p50_ms': round(
                _percentile(baseline_itls, 50) * 1e3, 2),
        }
    finally:
        eng.stop()


def _kv_bytes_per_position(cfg, quantized: bool) -> int:
    """KV bytes one cache position costs per layer per kv-head (k+v):
    the unit the fixed-memory comparison is stated in."""
    import numpy as np
    if quantized:
        return 2 * (cfg.head_dim * 1 + 4)   # int8 values + f32 scale
    return 2 * cfg.head_dim * np.dtype(cfg.dtype).itemsize


def _capacity_probe(cfg, params, *, dense_slots: int, max_len: int,
                    page_size: int, prompt_len: int, max_new: int,
                    vocab: int, quantize_kv: bool = True,
                    max_concurrency: int = 512) -> Dict[str, Any]:
    """Max concurrent requests at the DENSE cache's memory budget.

    Dense concurrency at this budget IS dense_slots (each slot
    reserves max_len positions no matter what requests need).  The
    paged pool with the same bytes holds n_pages pages; a request
    pins ceil((prompt + max_new - 1)/page_size) of them — the probe
    builds that engine and actually runs the full complement
    concurrently to completion.
    """
    import numpy as np

    from skypilot_tpu.serve import batching_engine
    budget_bytes = (dense_slots * max_len *
                    _kv_bytes_per_position(cfg, quantized=False))
    page_bytes = page_size * _kv_bytes_per_position(cfg, quantize_kv)
    n_pages = budget_bytes // page_bytes
    pages_per_request = -(-(prompt_len + max_new - 1) // page_size)
    paged_slots = min(int(n_pages // pages_per_request),
                      max_concurrency)
    eng = batching_engine.ContinuousBatchingEngine(
        cfg, params, max_len=max_len, slots=paged_slots,
        prefill_chunk=max(page_size, 16), kv_pages=int(n_pages) + 1,
        page_size=page_size, quantize_kv=quantize_kv,
        prefix_caching=False)
    rng = np.random.default_rng(0)
    peak_busy = 0
    try:
        eng.generate([1, 2, 3], 2, timeout=600)  # warm compiles
        handles = [
            eng.submit([int(x) for x in
                        rng.integers(1, vocab - 1, size=prompt_len)],
                       max_new)
            for _ in range(paged_slots)
        ]
        while not all(h.done.is_set() for h in handles):
            peak_busy = max(peak_busy, eng.stats()['busy_slots'])
            time.sleep(0.01)
        for h in handles:
            assert len(h.result(timeout=600)) == max_new
        stats = eng.stats()
    finally:
        eng.stop()
    return {
        'budget_bytes': int(budget_bytes),
        'page_size': page_size,
        'quantize_kv': quantize_kv,
        'kv_pages': int(n_pages),
        'pages_per_request': pages_per_request,
        'prompt_len': prompt_len,
        'max_new_tokens': max_new,
        'max_concurrent_dense': dense_slots,
        'max_concurrent_paged': paged_slots,
        'peak_busy_slots': peak_busy,
        'concurrency_ratio': round(paged_slots / max(dense_slots, 1),
                                   2),
        'pool_drained': stats['kv_pages_used'] == 0,
    }


def _prefix_probe(cfg, params, *, max_len: int, page_size: int,
                  chunk: int, prefix_len: int, vocab: int,
                  trials: int = 3,
                  quantize_kv: bool = True) -> Dict[str, Any]:
    """Shared-prefix TTFT: cold prefill once, then hits that adopt the
    cached pages and prefill only the unmatched tail."""
    import numpy as np

    from skypilot_tpu.serve import batching_engine
    pages_needed = -(-(prefix_len + 8) // page_size) * (trials + 3)
    eng = batching_engine.ContinuousBatchingEngine(
        cfg, params, max_len=max_len, slots=2, prefill_chunk=chunk,
        kv_pages=pages_needed + 8, page_size=page_size,
        quantize_kv=quantize_kv, prefix_caching=True)
    rng = np.random.default_rng(1)

    def ttft_of(prompt):
        handle = eng.submit(prompt, 4)
        handle.result(timeout=600)
        span = eng.span(handle.request_id)
        return span['ttft_ms'], span['prefix_hit_pages']

    try:
        # Warm EVERY compile on both paths (chunk-0 bucket, chunk
        # continuation, page insert, prefix seed) with a throwaway
        # prompt of the same length, measured afterwards on a prompt
        # the cache has never seen.
        warm = [int(x) for x in rng.integers(1, vocab - 1,
                                             size=prefix_len)]
        ttft_of(warm)
        ttft_of(warm)          # warms the hit path (seed compile)
        shared = [int(x) for x in rng.integers(1, vocab - 1,
                                               size=prefix_len)]
        ttft_cold, _ = ttft_of(shared)
        hits = [ttft_of(shared) for _ in range(trials)]
        hit_ttfts = sorted(t for t, _ in hits)
        ttft_hit = hit_ttfts[len(hit_ttfts) // 2]
        hit_pages = hits[0][1]
    finally:
        eng.stop()
    return {
        'prefix_len': prefix_len,
        'page_size': page_size,
        'prefill_chunk': chunk,
        'quantize_kv': quantize_kv,
        'ttft_cold_ms': round(ttft_cold, 3),
        'ttft_hit_ms': round(ttft_hit, 3),
        'ttft_hit_ratio': round(ttft_hit / max(ttft_cold, 1e-9), 4),
        'prefix_hit_pages': hit_pages,
    }


def _spec_probe(cfg, params, *, smoke: bool, vocab: int, seed: int,
                spec_tokens: int = 3) -> Dict[str, Any]:
    """Self-speculative decoding A/B on repetitive text.

    Periodic prompts push the tiny model's greedy decode into cycles
    — exactly the regime the n-gram prompt-lookup drafter targets.
    The SAME workload runs with drafting off (`spec_tokens=0`) and on
    (`spec_tokens=k`); accepted tokens all land in one verify tick,
    so the per-token gap (ITL) collapses while the token stream stays
    byte-identical (longest-exact-prefix acceptance under greedy)."""
    import numpy as np

    from skypilot_tpu.serve import batching_engine

    n_requests = 3 if smoke else 6
    max_new = 48 if smoke else 160
    prompt_len = 24 if smoke else 48
    page_size = 8
    max_len = -(-(prompt_len + max_new + 2) // page_size) * page_size
    rng = np.random.default_rng(seed)
    prompts = []
    for _ in range(n_requests):
        period = int(rng.integers(2, 5))
        motif = [int(x) for x in
                 rng.integers(1, vocab - 1, size=period)]
        prompts.append((motif * (prompt_len // period + 1))
                       [:prompt_len])

    def run(k: int):
        eng = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=max_len, slots=n_requests,
            prefill_chunk=max(prompt_len, 16),
            kv_pages=(n_requests + 1) * (max_len // page_size) + 4,
            page_size=page_size, prefix_caching=False,
            spec_tokens=k)
        try:
            # Warm every compile on the measured path (prefill
            # bucket, page insert, and the plain OR spec tick).
            eng.generate(prompts[0], 4, timeout=600)
            tracked = [_Tracked(p, max_new) for p in prompts]
            t0 = time.perf_counter()
            for t in tracked:
                t.submit_t = time.perf_counter()
                t.handle = eng.submit(t.prompt, t.max_new)
                t.handle.add_watcher(t.watcher)
            outputs = [t.handle.result(timeout=600) for t in tracked]
            wall = time.perf_counter() - t0
            stats = eng.stats()
        finally:
            eng.stop()
        itls = [g for t in tracked for g in t.itls]
        tokens = sum(len(o) for o in outputs)
        return {
            'tokens': tokens,
            'wall_s': round(wall, 3),
            'tokens_per_s': round(tokens / max(wall, 1e-9), 2),
            'itl_p50_ms': round(_percentile(itls, 50) * 1e3, 3),
            'itl_p99_ms': round(_percentile(itls, 99) * 1e3, 3),
        }, outputs, stats

    off, out_off, _ = run(0)
    on, out_on, stats = run(spec_tokens)
    return {
        'spec_tokens': spec_tokens,
        'requests': n_requests,
        'prompt_len': prompt_len,
        'max_new_tokens': max_new,
        'spec_off': off,
        'spec_on': on,
        'outputs_match': out_off == out_on,
        'spec_ticks': stats['spec_ticks'],
        'spec_proposed_tokens': stats['spec_proposed_tokens'],
        'spec_accepted_tokens': stats['spec_accepted_tokens'],
        'spec_accept_len_mean': stats['spec_accept_len_mean'],
        'itl_p50_speedup': round(
            off['itl_p50_ms'] / max(on['itl_p50_ms'], 1e-9), 3),
        'itl_p99_speedup': round(
            off['itl_p99_ms'] / max(on['itl_p99_ms'], 1e-9), 3),
    }


def _kernel_probe(cfg, params, *, smoke: bool, vocab: int,
                  seed: int) -> Dict[str, Any]:
    """Paged decode-kernel A/B: gather reference vs the Pallas
    paged-attention kernel on the same int8-paged workload.

    Off-TPU the Pallas path runs under the interpreter, so the
    numbers here pin PARITY (greedy outputs byte-identical) and
    presence — interpret-mode wall-clock is not a perf claim.  On a
    TPU backend the same section reads the fused kernel's tokens/s
    against the gather path."""
    import os

    import jax
    import numpy as np

    from skypilot_tpu.serve import batching_engine

    n_requests = 2
    max_new = 8 if smoke else 24
    prompt_len = 12 if smoke else 48
    page_size = 8
    max_len = -(-(prompt_len + max_new + 2) // page_size) * page_size
    rng = np.random.default_rng(seed)
    prompts = [[int(x) for x in
                rng.integers(1, vocab - 1, size=prompt_len)]
               for _ in range(n_requests)]
    interpret = jax.default_backend() != 'tpu'

    def run(kernel: str):
        # The kernel choice is resolved ONCE at engine construction
        # from SKYTPU_DECODE_KERNEL; pin it for the build, restore
        # the caller's environment after.
        saved = {k: os.environ.get(k)
                 for k in ('SKYTPU_DECODE_KERNEL',
                           'SKYTPU_PALLAS_INTERPRET')}
        os.environ['SKYTPU_DECODE_KERNEL'] = kernel
        if interpret:
            os.environ['SKYTPU_PALLAS_INTERPRET'] = '1'
        try:
            eng = batching_engine.ContinuousBatchingEngine(
                cfg, params, max_len=max_len, slots=n_requests,
                prefill_chunk=16,
                kv_pages=(n_requests + 1) * (max_len // page_size)
                + 4,
                page_size=page_size, quantize_kv=True,
                prefix_caching=False)
        finally:
            for key, value in saved.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value
        try:
            if eng.decode_kernel != kernel:
                raise RuntimeError(
                    f'engine resolved kernel {eng.decode_kernel!r}, '
                    f'wanted {kernel!r}')
            eng.generate(prompts[0], 2, timeout=600)  # warm compiles
            t0 = time.perf_counter()
            handles = [eng.submit(p, max_new) for p in prompts]
            outputs = [h.result(timeout=600) for h in handles]
            wall = time.perf_counter() - t0
        finally:
            eng.stop()
        tokens = sum(len(o) for o in outputs)
        return {
            'decode_kernel': kernel,
            'tokens': tokens,
            'wall_s': round(wall, 3),
            'tokens_per_s': round(tokens / max(wall, 1e-9), 2),
        }, outputs

    gather, out_gather = run('gather')
    pallas, out_pallas = run('pallas')
    return {
        'page_size': page_size,
        'quantize_kv': True,
        'prompt_len': prompt_len,
        'max_new_tokens': max_new,
        'interpret_mode': interpret,
        'kernels': {'gather': gather, 'pallas': pallas},
        'outputs_match': out_gather == out_pallas,
    }


def _run_disagg_config(*, replica_urls, roles, page_size, threshold,
                       long_prompt_len, chat_prompt_len, chat_max_new,
                       n_chat, n_bursts, burst_interval_s, vocab,
                       seed) -> Dict[str, Any]:
    """One routing-LB fleet over two ALREADY-RUNNING replica processes
    under the bursty mixed workload: N steady chat token streams
    decode while long prompts burst in Poisson-spaced.  Roles are an
    LB-side attribute, so the SAME replica processes serve both
    configs — the caller contrasts roles=['mixed','mixed']
    (role-blind) against ['prefill','decode'] (disaggregated + KV
    handoff)."""
    import numpy as np
    import requests

    from skypilot_tpu.observability import metrics as obs_metrics
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import router as router_lib

    def counter_total(name: str, **labels) -> float:
        parsed = obs_metrics.parse_exposition(obs_metrics.expose())
        total = 0.0
        for labelset, value in (parsed.get(name) or {}).items():
            d = dict(labelset)
            if all(d.get(k) == v for k, v in labels.items()):
                total += value
        return total

    handoff_ok_0 = counter_total('skytpu_lb_handoff_total',
                                 outcome='ok')
    handoff_fb_0 = counter_total('skytpu_lb_handoff_total',
                                 outcome='fallback')
    rng = np.random.default_rng(seed)
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1',
        router=router_lib.Router(threshold=threshold))
    try:
        lb.set_replicas([
            {'url': url, 'role': role, 'page_size': page_size}
            for url, role in zip(replica_urls, roles)])
        lb_port = lb.start()
        base = f'http://127.0.0.1:{lb_port}'

        def long_prompt():
            return [int(x) for x in rng.integers(
                1, vocab - 1, size=long_prompt_len)]

        # Warm the routed path for THIS fleet config (any cold compile
        # belongs to warmup, not the measured window).
        requests.post(f'{base}/generate',
                      json={'prompt_ids': [long_prompt()],
                            'max_new_tokens': 2}, timeout=300)

        # Steady chat decodes: each client keeps an SSE stream open
        # back-to-back (a finished conversation is immediately
        # replaced), recording every token arrival per session — gaps
        # are only ever measured WITHIN a session, never across the
        # reconnect seam.
        chat_sessions: List[List[float]] = []
        sessions_lock = threading.Lock()
        chat_stop = threading.Event()
        tokens_seen = [0]

        def chat_client(idx: int) -> None:
            session_rng = np.random.default_rng((seed, idx))
            while not chat_stop.is_set():
                prompt = [int(x) for x in session_rng.integers(
                    1, vocab - 1, size=chat_prompt_len)]
                times: List[float] = []
                with sessions_lock:
                    chat_sessions.append(times)
                try:
                    with requests.post(
                            f'{base}/generate_stream',
                            json={'prompt_ids': prompt,
                                  'max_new_tokens': chat_max_new},
                            stream=True, timeout=300) as resp:
                        for line in resp.iter_lines(chunk_size=16):
                            if chat_stop.is_set():
                                return
                            if line.startswith(b'data:') and \
                                    b'[DONE]' not in line:
                                times.append(time.perf_counter())
                                tokens_seen[0] += 1
                except requests.RequestException:
                    if not chat_stop.is_set():
                        time.sleep(0.01)

        chat_threads = [threading.Thread(target=chat_client, args=(i,))
                        for i in range(n_chat)]
        for t in chat_threads:
            t.start()
        deadline = time.time() + 60
        while tokens_seen[0] < 3 * n_chat and time.time() < deadline:
            time.sleep(0.01)

        # Long-prompt bursts, Poisson-spaced, while the chats decode.
        long_latencies: List[float] = []
        lat_lock = threading.Lock()

        def burst_client(prompt) -> None:
            t0 = time.perf_counter()
            try:
                requests.post(f'{base}/generate',
                              json={'prompt_ids': [prompt],
                                    'max_new_tokens': 2}, timeout=300)
            except requests.RequestException:
                return
            with lat_lock:
                long_latencies.append(
                    (time.perf_counter() - t0) * 1e3)

        t_burst0 = time.perf_counter()
        burst_threads = []
        for _ in range(n_bursts):
            thread = threading.Thread(target=burst_client,
                                      args=(long_prompt(),))
            thread.start()
            burst_threads.append(thread)
            time.sleep(float(rng.exponential(burst_interval_s)))
        for thread in burst_threads:
            thread.join()
        t_burst1 = time.perf_counter()
        time.sleep(0.1)
        chat_stop.set()
        for thread in chat_threads:
            thread.join(timeout=30)
    finally:
        lb.stop()
    # Chat ITL during the burst window: the number disaggregation
    # exists to protect.
    itls = []
    for times in chat_sessions:
        window = [x for x in times
                  if t_burst0 - 0.05 <= x <= t_burst1 + 0.1]
        itls.extend(b - a for a, b in zip(window, window[1:]))
    return {
        'roles': list(roles),
        'chat_streams': n_chat,
        'chat_tokens_in_burst_window': len(itls),
        'chat_itl_p50_ms': round(_percentile(itls, 50) * 1e3, 2),
        'chat_itl_p99_ms': round(_percentile(itls, 99) * 1e3, 2),
        'chat_itl_max_ms': round(max(itls, default=0.0) * 1e3, 2),
        'long_requests': len(long_latencies),
        'long_latency_p50_ms': round(
            _percentile(long_latencies, 50), 2),
        'long_latency_p99_ms': round(
            _percentile(long_latencies, 99), 2),
        'handoffs_ok': counter_total(
            'skytpu_lb_handoff_total', outcome='ok') - handoff_ok_0,
        'handoff_fallbacks': counter_total(
            'skytpu_lb_handoff_total',
            outcome='fallback') - handoff_fb_0,
    }


def _sp_prefill_probe(*, smoke: bool, model: str = 'tiny'
                      ) -> Dict[str, Any]:
    """Long-context prefill scaling with host count (ISSUE 9).

    Each host count runs `python -m skypilot_tpu.serve.slice_replica
    --bench-prefill` in its OWN subprocess pinned to `hosts x
    cores_per_host` CPU cores — the local stand-in for "each host
    brings its own chips": the sequence axis splits the quadratic
    attention across the slice, and the extra hosts' cores are what
    turn that split into wall-clock.  The pinned number is
    prefill_speedup_Nx = t(1 host) / t(N hosts); the tier-1 smoke
    floor-asserts the 2-host ratio."""
    import os
    import subprocess
    import sys

    prompt_len = 3072 if smoke else 8192
    host_counts = [1, 2] if smoke else [1, 2, 4]
    try:
        cores = sorted(os.sched_getaffinity(0))
    except AttributeError:
        cores = []
    cores_per_host = max(1, len(cores) // max(host_counts)) \
        if cores else 0
    results: Dict[int, Dict[str, Any]] = {}
    for hosts in host_counts:
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('PALLAS_AXON_POOL_IPS', None)
        env['XLA_FLAGS'] = (
            f'--xla_force_host_platform_device_count={hosts}')
        preexec = None
        if cores_per_host and hasattr(os, 'sched_setaffinity'):
            pinned = set(cores[:hosts * cores_per_host])
            preexec = (lambda p=pinned:
                       os.sched_setaffinity(0, p))  # noqa: E731
        proc = subprocess.run(
            [sys.executable, '-m',
             'skypilot_tpu.serve.slice_replica', '--bench-prefill',
             '--num-hosts', str(hosts), '--sequence', str(hosts),
             '--prompt-len', str(prompt_len), '--model', model,
             '--iters', '3' if smoke else '5'],
            env=env, capture_output=True, text=True, timeout=600,
            preexec_fn=preexec, check=True)
        results[hosts] = json.loads(proc.stdout.strip().splitlines()[-1])
    base = results[1]['prefill_s']
    out: Dict[str, Any] = {
        'prompt_len': prompt_len,
        'cores_per_host': cores_per_host,
        'per_hosts': {str(h): r for h, r in results.items()},
    }
    for hosts in host_counts[1:]:
        out[f'prefill_speedup_{hosts}x'] = round(
            base / max(results[hosts]['prefill_s'], 1e-9), 3)
    return out


def _spawn_replica(port: int, *, max_len: int, slots: int,
                   kv_pages: int, page_size: int, prefill_chunk: int,
                   cpus=None):
    """One model-server replica as a REAL subprocess (its own GIL, GC,
    and XLA thread pool — like a real fleet; in-process replicas bleed
    each other's pauses into the ITL measurements).  `cpus` pins the
    replica to a core set: two replicas on disjoint halves of the
    machine are the closest local stand-in for two hosts — without it,
    one replica's wide prefill steals the other's decode cores and the
    A/B measures this box's scheduler, not the routing policy."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    preexec = None
    if cpus and hasattr(os, 'sched_setaffinity'):
        preexec = lambda: os.sched_setaffinity(0, cpus)  # noqa: E731
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.serve.model_server',
         '--model', 'tiny', '--port', str(port),
         '--max-len', str(max_len), '--max-batch', str(slots),
         '--continuous-batching', '--kv-pages', str(kv_pages),
         '--page-size', str(page_size),
         '--prefill-chunk', str(prefill_chunk), '--quantize-kv'],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        preexec_fn=preexec)


def _disagg_probe(*, smoke: bool, vocab: int, seed: int
                  ) -> Dict[str, Any]:
    """Prefill/decode disaggregation A/B: the SAME bursty workload
    (steady chat SSE streams + Poisson long-prompt bursts) against a
    role-blind mixed fleet vs a prefill+decode fleet with KV page
    handoff — over real HTTP, with each replica its own process (int8
    KV: the production paged config, and the compact int8+scales wire
    format).  The claim under test: in-flight decode ITL p99 during
    bursts collapses when long prefills are kept off decode replicas
    (and the handed-off pages make the decode-side prefill a prefix
    hit)."""
    import socket
    import time as time_lib

    import requests

    # long_prompt_len is chosen PAGE-ALIGNED (prompt-1 divisible by
    # page_size): the handed-off pages then cover the whole prefilled
    # region and the decode replica admits the request as a FULL
    # prefix hit — zero prefill compute on the decode pool, the
    # best-case the page-granular wire format was designed for.
    # The prompt is long enough that each prefill chunk's compute
    # (attention is quadratic in context) dwarfs a decode tick AND the
    # decode-side page-adoption scatter; ~4 chunks per admission keeps
    # the stall-event count well above the p99 index so the percentile
    # reads the stalls, not scheduler noise.
    engine = dict(max_len=1024, slots=3, kv_pages=768, page_size=8,
                  prefill_chunk=224)
    knobs: Dict[str, Any] = dict(
        page_size=8, threshold=64, long_prompt_len=897,
        chat_prompt_len=8, chat_max_new=280, n_chat=2, n_bursts=10,
        burst_interval_s=0.15, vocab=vocab, seed=seed)
    if not smoke:
        engine = dict(max_len=2048, slots=3, kv_pages=1024,
                      page_size=8, prefill_chunk=480)
        knobs.update(long_prompt_len=1921, n_bursts=12,
                     chat_max_new=600, burst_interval_s=0.25)

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(('', 0))
            return s.getsockname()[1]

    import os
    ports = [free_port(), free_port()]
    try:
        cores = sorted(os.sched_getaffinity(0))
    except AttributeError:
        cores = []
    halves = [None, None]
    if len(cores) >= 2:
        halves = [set(cores[:len(cores) // 2]),
                  set(cores[len(cores) // 2:])]
    procs = [_spawn_replica(p, cpus=half, **engine)
             for p, half in zip(ports, halves)]
    urls = [f'http://127.0.0.1:{p}' for p in ports]
    try:
        # Readiness + warmup per replica: the long-prompt chunks, the
        # chat shape, and the handoff legs (export on replica 0,
        # import on replica 1) all compile before anything is timed.
        deadline = time_lib.time() + 300
        for url in urls:
            while True:
                try:
                    if requests.get(url + '/', timeout=2) \
                            .status_code == 200:
                        break
                except requests.RequestException:
                    pass
                if time_lib.time() > deadline:
                    raise RuntimeError(
                        f'replica {url} never became ready')
                time_lib.sleep(0.25)
        warm_long = list(range(1, knobs['long_prompt_len'] + 1))
        for url in urls:
            requests.post(f'{url}/generate',
                          json={'prompt_ids': [warm_long],
                                'max_new_tokens': 2}, timeout=300)
            requests.post(f'{url}/generate',
                          json={'prompt_ids':
                                [[1] * knobs['chat_prompt_len']],
                                'max_new_tokens': 2}, timeout=300)
        export = requests.post(
            f'{urls[0]}/prefill_export',
            json={'prompt_ids': warm_long,
                  'page_size': knobs['page_size']}, timeout=300)
        export.raise_for_status()
        requests.post(f'{urls[1]}/kv_import', json=export.json(),
                      timeout=300).raise_for_status()
        # Bytes-on-wire: the SAME export over the binary octet-stream
        # frame vs the JSON/base64 payload (the LB ships binary by
        # default; the ratio is the drop the binary wire buys).
        export_bin = requests.post(
            f'{urls[0]}/prefill_export',
            json={'prompt_ids': warm_long,
                  'page_size': knobs['page_size'],
                  'wire': 'binary'}, timeout=300)
        export_bin.raise_for_status()
        handoff_wire = {
            'json_bytes': len(export.content),
            'binary_bytes': len(export_bin.content),
            'bytes_ratio': round(
                len(export_bin.content) / max(len(export.content), 1),
                4),
        }
        mixed = _run_disagg_config(replica_urls=urls,
                                   roles=('mixed', 'mixed'), **knobs)
        disagg = _run_disagg_config(replica_urls=urls,
                                    roles=('prefill', 'decode'),
                                    **knobs)
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:  # pylint: disable=broad-except
                proc.kill()
    ratio = (disagg['chat_itl_p99_ms'] /
             max(mixed['chat_itl_p99_ms'], 1e-9))
    return {
        'long_prompt_len': knobs['long_prompt_len'],
        'prefill_chunk': engine['prefill_chunk'],
        'page_size': knobs['page_size'],
        'prefill_threshold': knobs['threshold'],
        'replicas_per_fleet': 2,
        'mixed': mixed,
        'disaggregated': disagg,
        'itl_p99_ratio_vs_mixed': round(ratio, 4),
        'handoff_wire': handoff_wire,
    }


def _batch_infer_probe(*, smoke: bool, vocab: int, seed: int
                       ) -> Dict[str, Any]:
    """Offline bulk inference riding the QoS floor (ISSUE 20): a
    saturating batch-infer driver streams a sharded manifest through
    the routing LB as QoS class `batch` while one interactive chat
    stream decodes.  A/B: the interactive stream's ITL on an idle
    fleet vs with the batch driver saturating — the floor the weighted
    QoS admission exists to protect — plus batch row throughput and
    how often the driver was shed-and-retried (the 429/Retry-After
    cooperative backoff contract)."""
    import json as json_lib
    import os
    import tempfile

    import numpy as np
    import requests

    from skypilot_tpu.batch import manifest as manifest_lib
    from skypilot_tpu.batch import runner as runner_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import model_server as model_server_lib
    from skypilot_tpu.serve import router as router_lib

    n_rows = 24 if smoke else 120
    max_new = 6 if smoke else 16
    chat_max_new = 32 if smoke else 300
    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix='skytpu-bench-batch-')
    input_path = os.path.join(tmp, 'input.jsonl')
    with open(input_path, 'w', encoding='utf-8') as f:
        for _ in range(n_rows):
            ids = [int(x) for x in rng.integers(1, vocab - 1, size=6)]
            f.write(json_lib.dumps({'prompt_ids': ids}) + '\n')
    run_dir = os.path.join(tmp, 'run')
    manifest_lib.build_manifest(input_path, run_dir, num_shards=4)

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    # Smoke keeps one replica: the floor A/B (driver saturating the
    # engine vs one interactive stream) needs contention, not a fleet,
    # and a second server is mostly tier-1 compile time.
    servers = [make_server()] if smoke else [make_server(), make_server()]
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1',
        router=router_lib.Router(threshold=10_000))
    shutdowns: List[Any] = []
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        lb.set_replicas([{'url': u, 'role': 'mixed'} for u in urls])
        lb_port = lb.start()
        base = f'http://127.0.0.1:{lb_port}'
        # Warm both replicas' shapes before anything is timed.
        for url in urls:
            requests.post(f'{url}/generate',
                          json={'prompt_ids': [[1, 2, 3, 4, 5, 6]],
                                'max_new_tokens': 2}, timeout=300)

        def chat_session(max_new_tokens: int) -> List[float]:
            """One interactive SSE stream; token arrival times."""
            times: List[float] = []
            prompt = [int(x) for x in
                      rng.integers(1, vocab - 1, size=4)]
            with requests.post(f'{base}/generate_stream',
                               json={'prompt_ids': prompt,
                                     'max_new_tokens': max_new_tokens},
                               stream=True, timeout=300) as resp:
                for line in resp.iter_lines(chunk_size=16):
                    if line.startswith(b'data:') and \
                            b'[DONE]' not in line:
                        times.append(time.perf_counter())
            return times

        def itls_ms(times: List[float]) -> List[float]:
            return [(b - a) * 1e3 for a, b in zip(times, times[1:])]

        # A: the interactive stream on an idle fleet.
        idle_itls = itls_ms(chat_session(chat_max_new))

        # B: same stream with the batch driver saturating the pool.
        job = runner_lib.BatchInferJob(run_dir, base,
                                       max_new_tokens=max_new,
                                       inflight=8)
        summary_holder: Dict[str, Any] = {}

        def drive() -> None:
            summary_holder.update(job.run())

        driver = threading.Thread(target=drive, daemon=True)
        t0 = time.perf_counter()
        driver.start()
        loaded_itls: List[float] = []
        while True:  # at least one full interactive session under load
            loaded_itls.extend(itls_ms(chat_session(chat_max_new)))
            if not driver.is_alive():
                break
        driver.join(timeout=600)
        elapsed = time.perf_counter() - t0
    finally:
        lb.stop()
        for stop in shutdowns:
            stop()
        for server in servers:
            server.close()
    rows_done = summary_holder.get('rows') or 0
    return {
        'rows': rows_done,
        'shards': summary_holder.get('shards_total'),
        'duplicates_dropped': summary_holder.get('duplicates_dropped'),
        'driver_retries': summary_holder.get('retries'),
        'elapsed_s': round(elapsed, 3),
        'rows_per_s': round(rows_done / max(elapsed, 1e-9), 3),
        'idle_itl_p50_ms': round(_percentile(idle_itls, 50), 2),
        'idle_itl_p99_ms': round(_percentile(idle_itls, 99), 2),
        'loaded_itl_p50_ms': round(_percentile(loaded_itls, 50), 2),
        'loaded_itl_p99_ms': round(_percentile(loaded_itls, 99), 2),
        'itl_p99_ratio_vs_idle': round(
            _percentile(loaded_itls, 99) /
            max(_percentile(idle_itls, 99), 1e-9), 4),
    }


def _dynamic_roles_probe(cfg, params, *, smoke: bool, vocab: int,
                         seed: int) -> Dict[str, Any]:
    """Dynamic fractional role budgets vs static roles (ISSUE 17)
    under an adversarial shifting mix: an all-prefill burst (long
    prompts, 2 new tokens) flips mid-window into an all-decode burst
    (short prompts, long generations).  One replica must serve the
    whole shift — the per-replica core of the fleet A/B (the chaos
    scenario `workload_flip_morph` covers the fleet/LB layer; here the
    replica is an in-process engine so the measurement is engine
    capacity, not HTTP or GIL artifacts).  Static keeps a launch-time
    pure-role budget through the shift — BOTH pure roles are measured,
    and dynamic is scored against the better one, so the baseline is
    the strongest static choice, not a strawman: whichever pure role
    you pin, the other phase starves at its 1-token liveness floor.
    Dynamic gets what the controller's rebalancer pushes over
    /role_budget: prefill-leaning split while the burst is prefill,
    flipped in place (version-stamped, warm weights, no restart) to
    decode-leaning when the workload flips.  Headline:
    in_window_tokens_ratio (prompt + generated tokens of requests
    COMPLETED inside the fixed window, dynamic / best static).  The
    probe then replays the same prompts through a budget-flipping
    engine non-contended and byte-compares against an unclamped run:
    budgets may only reschedule work, never change tokens."""
    import itertools

    import numpy as np

    from skypilot_tpu.serve import batching_engine
    from skypilot_tpu.serve import scheduler as scheduler_lib

    slots = 8
    chunk = 32
    max_len = 96 if smoke else 224
    long_len = 64 if smoke else 160
    short_len = 4
    long_max_new = 2
    short_max_new = 40 if smoke else 48
    # The prefill burst is a wash by construction (the prefill-pinned
    # static and the prefill-leaning dynamic run the same budget); the
    # decode burst is where budget-matching pays, so it gets the
    # longer half of the window.
    phase_prefill_s = 0.6 if smoke else 2.0
    phase_decode_s = 1.8 if smoke else 5.0
    workers = 2 * slots
    ver = itertools.count(1)

    engine = batching_engine.ContinuousBatchingEngine(
        cfg, params, max_len=max_len, slots=slots,
        prefill_chunk=chunk)
    try:
        budget = scheduler_lib.RoleBudget

        # Warm every compile before any measured window — including
        # the SHRUNK chunk widths a decode-leaning budget clamps
        # prefill to (a 6-token budget buckets pieces at widths 8/6/4,
        # the 1-token pure-decode floor at width 1; cold, each is a
        # fresh XLA compile landing right in the window).  The one
        # engine is reused across configs, so all of them are equally
        # warm.
        engine.generate(list(range(1, long_len + 1)), long_max_new,
                        timeout=600)
        engine.generate(list(range(1, short_len + 1)), 4, timeout=600)
        engine.set_role_budget(budget.from_split(
            0.1, slots=slots, prefill_chunk=chunk, version=next(ver)))
        engine.generate(list(range(1, long_len + 1)), long_max_new,
                        timeout=600)
        engine.set_role_budget(budget.for_role(
            'decode', slots=slots, prefill_chunk=chunk,
            version=next(ver)))
        engine.generate(list(range(1, short_len + 1)), 4, timeout=600)
        engine.set_role_budget(None)

        def run_config(mode: str) -> Dict[str, Any]:
            swaps0 = engine.stats()['budget_swaps']
            if mode == 'dynamic':
                # The rebalancer's clamped prefill-leaning extreme;
                # flipped to decode-leaning mid-window below.
                engine.set_role_budget(budget.from_split(
                    0.9, slots=slots, prefill_chunk=chunk,
                    version=next(ver)))
            else:
                engine.set_role_budget(budget.for_role(
                    mode, slots=slots, prefill_chunk=chunk,
                    version=next(ver)))
            lock = threading.Lock()
            totals = {'in_window_tokens': 0, 'requests': 0,
                      'prefill_phase_tokens': 0,
                      'decode_phase_tokens': 0}
            t0 = time.perf_counter()
            t_flip = t0 + phase_prefill_s
            t_end = t_flip + phase_decode_s

            def client(idx: int) -> None:
                wrng = np.random.default_rng((seed, idx))
                while True:
                    now = time.perf_counter()
                    if now >= t_end:
                        return
                    prefill_phase = now < t_flip
                    if prefill_phase:
                        prompt = [int(x) for x in wrng.integers(
                            1, vocab - 1, size=long_len)]
                        max_new = long_max_new
                    else:
                        prompt = [int(x) for x in wrng.integers(
                            1, vocab - 1, size=short_len)]
                        max_new = short_max_new
                    out = engine.generate(prompt, max_new,
                                          timeout=120)
                    if time.perf_counter() <= t_end:
                        with lock:
                            totals['in_window_tokens'] += \
                                len(prompt) + len(out)
                            totals['requests'] += 1
                            key = ('prefill_phase_tokens'
                                   if prefill_phase
                                   else 'decode_phase_tokens')
                            totals[key] += len(prompt) + len(out)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(workers)]
            for t in threads:
                t.start()
            if mode == 'dynamic':
                # The mid-window rebalance: the workload flipped, so
                # the budget flips with it (in place, version-ordered
                # — running decodes finish, no restart).
                time.sleep(max(0.0, t_flip - time.perf_counter()))
                engine.set_role_budget(budget.from_split(
                    0.1, slots=slots, prefill_chunk=chunk,
                    version=next(ver)))
            for t in threads:
                t.join(timeout=180)
            totals['budget_swaps'] = (
                engine.stats()['budget_swaps'] - swaps0)
            return totals

        # The decode-pinned static is strictly the weaker baseline on
        # this mix (its prefill burst crawls at the 1-token floor); the
        # smoke skips it for tier-1 wall-clock and scores dynamic
        # against the prefill pin — the full run measures all three.
        static_prefill = run_config('prefill')
        static_decode = None if smoke else run_config('decode')
        dynamic = run_config('dynamic')

        # Token-exactness, non-contended: the SAME prompts through an
        # unclamped engine vs one whose budget flips between requests.
        # Budgets reschedule; they must never touch the token stream.
        exact_rng = np.random.default_rng((seed, 104729))
        exact_prompts = [
            [int(x) for x in exact_rng.integers(1, vocab - 1, size=n)]
            for n in (short_len, long_len, short_len + 3, long_len // 2)
        ]
        engine.set_role_budget(None)
        reference = [engine.generate(p, 8, timeout=120)
                     for p in exact_prompts]
        flipped = []
        for i, prompt in enumerate(exact_prompts):
            role = ('prefill', 'decode', 'mixed')[i % 3]
            engine.set_role_budget(budget.for_role(
                role, slots=slots, prefill_chunk=chunk,
                version=next(ver)))
            flipped.append(engine.generate(prompt, 8, timeout=120))
    finally:
        engine.stop()
    statics = [s for s in (static_prefill, static_decode)
               if s is not None]
    best_static = max(s['in_window_tokens'] for s in statics)
    ratio = dynamic['in_window_tokens'] / max(best_static, 1)
    out = {
        'slots': slots,
        'prefill_chunk': chunk,
        'long_prompt_len': long_len,
        'short_prompt_len': short_len,
        'phase_prefill_s': phase_prefill_s,
        'phase_decode_s': phase_decode_s,
        'workers': workers,
        'static_prefill': static_prefill,
        'dynamic': dynamic,
        'best_static_in_window_tokens': best_static,
        'in_window_tokens_ratio': round(ratio, 4),
        'outputs_match': flipped == reference,
    }
    if static_decode is not None:
        out['static_decode'] = static_decode
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--slots', type=int, default=4)
    parser.add_argument('--max-len', type=int, default=512)
    parser.add_argument('--requests', type=int, default=48)
    parser.add_argument('--rate', type=float, default=150.0,
                        help='Poisson arrival rate (requests/s).  The '
                             'default SATURATES the CPU tiny config so '
                             'tokens/s measures engine capacity, not '
                             'offered load; lower it to probe latency '
                             'at sub-saturation.')
    parser.add_argument('--max-new-tokens', type=int, default=32)
    parser.add_argument('--prompt-lens', default='8,24,64,128',
                        help='Comma-separated prompt-length mix.')
    parser.add_argument('--prefill-chunk', type=int, default=256)
    parser.add_argument('--stall-prompt-len', type=int, default=2048,
                        help='Long-admission prompt for the ITL stall '
                             'probe.')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--skip-legacy', action='store_true',
                        help='Skip the pre-pipeline A/B run.')
    parser.add_argument('--skip-stall-probe', action='store_true')
    parser.add_argument('--skip-paged-probes', action='store_true',
                        help='Skip the paged-KV capacity and '
                             'prefix-cache TTFT probes.')
    parser.add_argument('--skip-disagg-probe', action='store_true',
                        help='Skip the prefill/decode disaggregation '
                             'A/B (two replicas + routing LB over '
                             'real HTTP).')
    parser.add_argument('--skip-spec-probe', action='store_true',
                        help='Skip the self-speculative decoding A/B '
                             '(repetitive-text ITL + acceptance).')
    parser.add_argument('--skip-kernel-probe', action='store_true',
                        help='Skip the paged decode-kernel A/B '
                             '(gather vs Pallas parity/perf).')
    parser.add_argument('--skip-dynamic-roles', action='store_true',
                        help='Skip the dynamic fractional-role-budget '
                             'A/B (static pure pools vs in-place '
                             'budget rebalancing under a shifting '
                             'prefill/decode mix).')
    parser.add_argument('--skip-sp-probe', action='store_true',
                        help='Skip the multi-host sequence-parallel '
                             'long-context prefill scaling probe '
                             '(subprocess per host count).')
    parser.add_argument('--skip-batch-probe', action='store_true',
                        help='Skip the offline batch-infer QoS-floor '
                             'probe (saturating batch driver vs one '
                             'interactive stream, A/B ITL).')
    parser.add_argument('--page-size', type=int, default=16,
                        help='KV page size for the paged probes.')
    parser.add_argument('--prefix-len', type=int, default=256,
                        help='Shared system-prompt length for the '
                             'prefix-cache TTFT probe.')
    parser.add_argument('--smoke', action='store_true',
                        help='Seconds-scale config for CI '
                             '(tests/unit/test_bench_serve.py).')
    parser.add_argument('--pin', action='store_true',
                        help='With --smoke: write the pinned '
                             'BENCH_serve_smoke.json at the repo root. '
                             'Default smoke output goes to a temp path '
                             'so every tier-1 run does not churn the '
                             'pinned file.')
    parser.add_argument('--out', default=None,
                        help='Output JSON path (default '
                             'BENCH_serve.json; --smoke defaults to a '
                             'temp path unless --pin).')
    args = parser.parse_args()
    if args.smoke:
        # Seconds-scale but still SATURATING (offered load well above
        # the legacy engine's capacity) so speedup_vs_legacy measures
        # the decode loop, not the arrival process.
        args.requests = 32
        args.rate = 400.0
        args.max_new_tokens = 16
        args.prompt_lens = '4,8,16'
        args.max_len = 64
        args.prefill_chunk = 32
        args.stall_prompt_len = 96
        args.page_size = 8
        args.prefix_len = 96
    if args.out:
        out_path = args.out
    elif args.smoke:
        # Smoke runs on every tier-1 pass; writing the pinned file
        # each time was pure VCS churn — temp by default, --pin to
        # refresh the committed sample.
        if args.pin:
            out_path = 'BENCH_serve_smoke.json'
        else:
            import os
            import tempfile
            out_path = os.path.join(
                tempfile.gettempdir(),
                f'bench_serve_smoke-{os.getpid()}.json')
    else:
        out_path = 'BENCH_serve.json'

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.models import configs
    from skypilot_tpu.serve import batching_engine

    cfg = configs.get_config(args.model)
    from skypilot_tpu.models.transformer import Transformer
    params = nn.meta.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))['params'])
    vocab = cfg.vocab_size
    prompt_lens = [int(x) for x in args.prompt_lens.split(',')]

    # --smoke: serve /metrics on loopback and sample it around the
    # pipelined run (the observability signal the smoke asserts on).
    metrics_port = None
    metrics_shutdown = None
    scrape_samples: List[Dict[str, Any]] = []
    if args.smoke:
        from skypilot_tpu.observability import metrics as obs_metrics
        metrics_port, metrics_shutdown = (
            obs_metrics.start_exposition_server())

    results: Dict[str, Any] = {}
    profile_snapshot: Optional[Dict[str, Any]] = None
    for mode, pipelined in (('pipelined', True), ('legacy', False)):
        if mode == 'legacy' and args.skip_legacy:
            continue
        rng = np.random.default_rng(args.seed)
        workload = _workload(rng, args.requests, args.rate, prompt_lens,
                             args.max_new_tokens, vocab)
        eng = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=args.max_len, slots=args.slots,
            prefill_chunk=args.prefill_chunk, pipelined=pipelined)
        try:
            # Warm every compile (tick, buckets, chunk) outside the
            # timed region with the REAL shapes — including the top
            # bucket the +25% prompt-length jitter can reach.
            warm_lens = sorted(set(prompt_lens) |
                               {int(max(prompt_lens) * 1.25) + 1})
            for base in warm_lens:
                eng.generate(list(range(1, base + 1)),
                             min(4, args.max_new_tokens), timeout=600)
            scraper = None
            if mode == 'pipelined' and metrics_port is not None:
                scrape_samples.append(_scrape_metrics(metrics_port))

                def _mid_scrape():
                    time.sleep(0.3)  # land inside the ~seconds run
                    scrape_samples.append(_scrape_metrics(metrics_port))

                scraper = threading.Thread(target=_mid_scrape)
                scraper.start()
            result = _run_load(eng, workload)
            if scraper is not None:
                scraper.join()
                scrape_samples.append(_scrape_metrics(metrics_port))
            if mode == 'pipelined':
                # Tick-phase attribution for the history record (the
                # perf-regression observatory keys breakdowns to runs).
                profile_snapshot = eng.profile()
        finally:
            eng.stop()
        results[mode] = result
    if metrics_shutdown is not None:
        metrics_shutdown()

    payload: Dict[str, Any] = {
        'metric': 'serve_decode_tokens_per_sec',
        'value': results['pipelined']['tokens_per_s'],
        'unit': 'tokens/s',
        'config': {
            'model': args.model,
            'slots': args.slots,
            'max_len': args.max_len,
            'requests': args.requests,
            'poisson_rate': args.rate,
            'max_new_tokens': args.max_new_tokens,
            'prompt_lens': prompt_lens,
            'prefill_chunk': args.prefill_chunk,
            'backend': jax.default_backend(),
        },
        'pipelined': results['pipelined'],
    }
    if 'legacy' in results:
        payload['legacy'] = results['legacy']
        legacy_tps = max(results['legacy']['tokens_per_s'], 1e-9)
        payload['speedup_vs_legacy'] = round(
            results['pipelined']['tokens_per_s'] / legacy_tps, 2)

    if scrape_samples:
        # The observability contract of the smoke: key series exist,
        # the latency histograms are exposed, and the counters are
        # monotone (and actually advanced) across the run's scrapes.
        for key in ('ticks', 'decode_tokens'):
            values = [s[key] for s in scrape_samples]
            if any(b < a for a, b in zip(values, values[1:])):
                raise RuntimeError(
                    f'/metrics counter {key} went BACKWARDS across '
                    f'scrapes: {values}')
            if values[-1] <= values[0]:
                raise RuntimeError(
                    f'/metrics counter {key} did not advance over the '
                    f'pipelined run: {values}')
        if not all(s['histograms_present'] for s in scrape_samples):
            raise RuntimeError(
                'queue-wait/ITL/TTFT histograms missing from /metrics')
        payload['metrics_scrape'] = {
            'samples': scrape_samples,
            'series_monotone': True,
        }

    if not args.skip_stall_probe:
        chunk_s = _measure_chunk_compute(
            cfg, params, args.prefill_chunk,
            args.stall_prompt_len + 64, vocab)
        max_new_bg = 80 if args.smoke else 400
        chunked = _stall_probe(
            cfg, params, slots=args.slots,
            prompt_len=args.stall_prompt_len,
            chunk=args.prefill_chunk, max_new_bg=max_new_bg,
            vocab=vocab, pipelined_chunked=True)
        unchunked = _stall_probe(
            cfg, params, slots=args.slots,
            prompt_len=args.stall_prompt_len,
            chunk=args.prefill_chunk, max_new_bg=max_new_bg,
            vocab=vocab, pipelined_chunked=False)
        # The engine runs at most one chunk between ticks, so a running
        # decode's worst gap is one chunk + one tick (+ host noise):
        # bound it by one chunk's compute plus a few baseline ITLs.
        bound_ms = round(chunk_s * 1e3 +
                         max(5 * chunked['baseline_itl_p50_ms'], 50.0),
                         2)
        payload['chunked_prefill_stall'] = {
            'stall_prompt_len': args.stall_prompt_len,
            'prefill_chunk': args.prefill_chunk,
            'chunk_compute_ms': round(chunk_s * 1e3, 2),
            'max_itl_during_admission_ms':
                chunked['max_itl_during_admission_ms'],
            'baseline_itl_p50_ms': chunked['baseline_itl_p50_ms'],
            'bound_ms': bound_ms,
            'stall_bounded_by_chunk':
                chunked['max_itl_during_admission_ms'] <= bound_ms,
            'unchunked_max_itl_ms':
                unchunked['max_itl_during_admission_ms'],
        }

    if not args.skip_paged_probes:
        ps = args.page_size
        payload['paged_capacity'] = _capacity_probe(
            cfg, params, dense_slots=args.slots,
            max_len=args.max_len, page_size=ps,
            prompt_len=8, max_new=8, vocab=vocab, quantize_kv=True,
            # Smoke caps concurrency at 16 (a 4x ratio already proves
            # the mechanism in seconds); the full run lets it ride.
            max_concurrency=16 if args.smoke else 256)
        probe_max_len = -(-(args.prefix_len + 16) // ps) * ps
        payload['prefix_cache'] = _prefix_probe(
            cfg, params, max_len=probe_max_len, page_size=ps,
            chunk=max(ps, 8), prefix_len=args.prefix_len,
            vocab=vocab, quantize_kv=True)

    if not args.skip_spec_probe:
        payload['spec_decode'] = _spec_probe(
            cfg, params, smoke=args.smoke, vocab=vocab,
            seed=args.seed)

    if not args.skip_kernel_probe:
        payload['paged_kernel'] = _kernel_probe(
            cfg, params, smoke=args.smoke, vocab=vocab,
            seed=args.seed)

    if not args.skip_disagg_probe:
        payload['disaggregation'] = _disagg_probe(
            smoke=args.smoke, vocab=vocab, seed=args.seed)

    if not args.skip_dynamic_roles:
        payload['dynamic_roles'] = _dynamic_roles_probe(
            cfg, params, smoke=args.smoke, vocab=vocab,
            seed=args.seed)

    if not args.skip_sp_probe:
        payload['sp_prefill'] = _sp_prefill_probe(smoke=args.smoke,
                                                  model=args.model)

    if not args.skip_batch_probe:
        payload['batch_infer'] = _batch_infer_probe(
            smoke=args.smoke, vocab=vocab, seed=args.seed)

    line = json.dumps(payload)
    print(line)
    with open(out_path, 'w', encoding='utf-8') as f:
        f.write(line + '\n')
    _append_history(args, payload, profile_snapshot)


def _append_history(args, payload: Dict[str, Any],
                    profile_snapshot: Optional[Dict[str, Any]]) -> None:
    """One run record into the perf-regression observatory
    (BENCH_history.jsonl; `sky bench diff` consumes it).  The
    COMMITTED history only grows behind --pin (a blessed run) or an
    explicit SKYTPU_BENCH_HISTORY_PATH — tier-1 runs this script
    (smoke AND full probes) on every pass and must not churn the
    repo; unblessed runs land in a throwaway per-process path."""
    import os
    import tempfile

    from skypilot_tpu.observability import bench_history
    path = None
    if (not args.pin and
            not os.environ.get('SKYTPU_BENCH_HISTORY_PATH')):
        path = os.path.join(
            tempfile.gettempdir(),
            f'bench_serve_history-{os.getpid()}.jsonl')
    pipelined = payload.get('pipelined') or {}
    phases = None
    if profile_snapshot:
        phases = {name: agg.get('total_s')
                  for name, agg in
                  (profile_snapshot.get('phases') or {}).items()}
    record = {
        'source': 'bench_serve',
        'metric': payload['metric'],
        'value': payload['value'],
        'unit': payload['unit'],
        'config': payload['config'],
        'tokens_per_s': pipelined.get('tokens_per_s'),
        'ttft_p50_ms': pipelined.get('ttft_p50_ms'),
        'ttft_p99_ms': pipelined.get('ttft_p99_ms'),
        'itl_p50_ms': pipelined.get('itl_p50_ms'),
        'itl_p99_ms': pipelined.get('itl_p99_ms'),
        'speedup_vs_legacy': payload.get('speedup_vs_legacy'),
        'phases': phases,
        'profiled_ticks': (profile_snapshot or {}).get('ticks'),
        'batch_rows_per_s':
            (payload.get('batch_infer') or {}).get('rows_per_s'),
        'batch_itl_p99_ratio':
            (payload.get('batch_infer') or {}).get(
                'itl_p99_ratio_vs_idle'),
    }
    try:
        where = bench_history.append_record(record, path)
        print(f'# bench history appended: {where}')
    except OSError as e:
        print(f'# bench history append failed: {e}')


if __name__ == '__main__':
    main()
