"""Benchmark: flagship-model training throughput on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no training-throughput numbers (BASELINE.md —
`published: {}`), so vs_baseline is reported against the MFU-derived
roofline expectation for the detected chip (1.0 == hitting 40% MFU,
a typical well-tuned TPU training MFU).

Robustness contract (VERDICT round-1 item 1): the JSON line is emitted
even when the pre-registered TPU platform fails to initialize or hangs.
The benchmark itself runs in a subprocess; the orchestrator tries the
ambient environment first (real TPU via the tunnel), then falls back to
platform autodetection, then to pure CPU — each attempt bounded by a
timeout — and re-prints the first JSON line an attempt produces.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
_METRIC = 'llama_train_tokens_per_sec_per_chip'
# Shared with the dryrun contract: env vars that (re)register the
# remote-compile PJRT plugin and must be scrubbed for fallback attempts.
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
from __graft_entry__ import _PLUGIN_ENV_VARS  # noqa: E402


def _param_count(params) -> int:
    import jax
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s for known TPU generations (fallback: v5e).

    Matched against real device_kind strings ('TPU v5 lite', 'TPU v5p',
    'TPU v6 lite', ...) — most specific key first.
    """
    kind = getattr(device, 'device_kind', '').lower()
    table = (
        ('v6 lite', 918e12), ('v6e', 918e12),
        ('v5 lite', 197e12), ('v5litepod', 197e12), ('v5e', 197e12),
        ('v5p', 459e12), ('v4', 275e12), ('v3', 123e12), ('v2', 45e12),
    )
    for key, val in table:
        if key in kind:
            return val
    return 197e12


def _run_config(cfg, batch: int, seq: int, n_steps: int, tcfg=None):
    """Compile + warm up + time one training config.

    Returns (tokens_per_sec, n_params, final_loss, peak_bytes).
    `tcfg` threads the hot-path knobs (fused CE, accumulation) into
    train_step; batches stream through the double-buffered
    DevicePrefetcher (data/prefetch.py) so step N+1's host->device
    transfer overlaps step N's compute — the same path the gang job
    contract uses.  peak_bytes is the compiled step's temp allocation
    (XLA CompiledMemoryStats; None when the backend hides it).

    Synchronisation contract (VERDICT round-2 weak #3):
    `jax.block_until_ready` was observed NOT to synchronize on the
    relay TPU platform (a loop timed that way yielded a physically
    impossible 132 MFU), so the timed region ends with a `device_get`
    of the FINAL step's loss.  That value transitively depends on every
    prior step (each step consumes the previous step's donated
    TrainState), so fetching it cannot complete before all timed steps
    actually executed on the chip — while avoiding a per-step host
    round-trip (~100 ms through the relay tunnel, measured — it
    inflated step time ~35%).
    """
    import functools

    import jax
    import numpy as np

    from skypilot_tpu.data.prefetch import prefetch_to_device
    from skypilot_tpu.models.train import TrainConfig
    from skypilot_tpu.models.train import create_train_state
    from skypilot_tpu.models.train import train_step

    state, _ = create_train_state(cfg, tcfg or TrainConfig(),
                                  batch_size=batch, seq_len=seq)
    n_params = _param_count(state.params)
    step_fn = functools.partial(train_step, tcfg=tcfg)
    jitted = jax.jit(step_fn, donate_argnums=(0,))

    rng = np.random.default_rng(0)

    def host_batches(n):
        for _ in range(n):
            yield {'tokens': rng.integers(
                0, cfg.vocab_size,
                size=(batch, seq + 1)).astype(np.int32)}

    warmup = 2
    # One AOT compile serves both the memory stats and execution (a
    # second trace through jit would double the TPU compile time).
    first = next(prefetch_to_device(host_batches(1)))
    compiled = jitted.lower(state, first).compile()
    from skypilot_tpu.models.train import compiled_peak_memory
    # Also feeds the skytpu_train_peak_memory_bytes gauge.
    peak_bytes = compiled_peak_memory(compiled)

    prefetched = prefetch_to_device(host_batches(warmup + n_steps))
    for _ in range(warmup):
        state, metrics = compiled(state, next(prefetched))
    float(jax.device_get(metrics['loss']))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = compiled(state, next(prefetched))
    final_loss = float(jax.device_get(metrics['loss']))
    dt = time.perf_counter() - t0
    return batch * seq * n_steps / dt, n_params, final_loss, peak_bytes


def main() -> None:
    import jax

    from skypilot_tpu.models import configs

    dev = jax.devices()[0]
    # The TPU plugin may register under a custom platform name (e.g. a
    # tunnel), so also accept a TPU device_kind; GPU/CPU take the small
    # fallback path (the MFU roofline table is TPU-only).
    on_tpu = (jax.default_backend() == 'tpu' or
              'tpu' in getattr(dev, 'device_kind', '').lower())
    if on_tpu:
        base = configs.get_config('small', logits_in_f32=False)
        batch, seq = 16, 1024
        # Fastest schedule first; each step down trades flops for HBM.
        # 'small' at b=16/s=1024 is estimated to fit without remat on a
        # 16 GB v5e but the estimate is not a guarantee, so OOM (or any
        # config-specific failure) falls through to the next schedule
        # rather than burning the whole TPU attempt.
        candidates = [
            ('noremat+lmbf16', base.replace(remat=False)),
            ('dots+lmbf16', base.replace(remat_policy='dots')),
            ('full+lmbf16', base),
        ]
        n_steps = 20
    else:  # CI / laptop fallback
        # vocab 8192 (vs tiny's 256) makes the logits tensor the
        # dominant live buffer, so the fused-CE memory drop is visible
        # even at CPU scale.
        candidates = [('tiny-v8k',
                       configs.get_config('tiny', vocab_size=8192))]
        batch, seq = 4, 128
        n_steps = 3

    tokens_per_sec = n_params = final_loss = peak_bytes = None
    config_name = cfg_used = None
    for i, (name, cfg) in enumerate(candidates):
        try:
            tokens_per_sec, n_params, final_loss, peak_bytes = \
                _run_config(cfg, batch, seq, n_steps)
            config_name, cfg_used = name, cfg
            break
        except Exception as e:  # pylint: disable=broad-except
            # Only a memory-style failure means "try a leaner
            # schedule".  Anything else (dead relay, runtime crash)
            # would fail every candidate identically — propagate so the
            # orchestrator's platform fallback runs instead of burning
            # 3 more compiles against a broken backend.
            msg = f'{type(e).__name__}: {e}'
            oom_like = ('RESOURCE_EXHAUSTED' in msg or 'OOM' in msg or
                        'out of memory' in msg.lower())
            print(f'# bench config {name} failed: {msg[:300]}',
                  file=sys.stderr)
            if not oom_like or i == len(candidates) - 1:
                raise
    assert tokens_per_sec is not None  # loop breaks on success or raises

    # Fused linear+CE pass over the SAME schedule (models/losses.py):
    # the [b,s,V] logits tensor never materializes.  Best-effort — a
    # fused failure must not cost the unfused number already in hand.
    from skypilot_tpu.models.train import TrainConfig
    fused_tps = fused_peak = None
    try:
        chunk = min(8192, max(1024, cfg_used.vocab_size // 8))
        fused_tps, _, fused_loss, fused_peak = _run_config(
            cfg_used, batch, seq, n_steps,
            tcfg=TrainConfig(fused_ce=True, vocab_chunk=chunk))
        print(f'# fused CE: {fused_tps:.1f} tok/s '
              f'loss={fused_loss:.3f} peak={fused_peak}', file=sys.stderr)
    except Exception as e:  # pylint: disable=broad-except
        print(f'# fused CE attempt failed: '
              f'{type(e).__name__}: {e}'[:300], file=sys.stderr)

    best_tps = max(tokens_per_sec, fused_tps or 0.0)
    # Training FLOPs/token ~= 6 * params; MFU vs chip roofline.
    achieved_flops = 6.0 * n_params * best_tps
    mfu = achieved_flops / _peak_flops(dev)
    vs_baseline = mfu / 0.40  # 1.0 == 40% MFU (well-tuned TPU training)

    # Self-describing artifact (ADVICE round-2): device + sync method
    # ride in the JSON itself so a CPU fallback can never be mistaken
    # for a TPU number by scoreboard consumers reading 'parsed' alone.
    print(json.dumps({
        'metric': _METRIC,
        'value': round(best_tps, 1),
        'unit': 'tokens/s',
        'vs_baseline': round(vs_baseline, 3),
        'device': dev.device_kind,
        'mfu': round(mfu, 4),
        'config': config_name,
        'tokens_per_sec_unfused': round(tokens_per_sec, 1),
        'tokens_per_sec_fused': (round(fused_tps, 1)
                                 if fused_tps is not None else None),
        'peak_bytes_unfused': peak_bytes,
        'peak_bytes_fused': fused_peak,
        'synced_timing': 'device_get_final_loss_chained',
    }))
    print(f'# device={dev.device_kind} config={config_name} '
          f'params={n_params/1e6:.1f}M mfu={mfu:.3f} '
          f'loss={final_loss:.3f}', file=sys.stderr)
    # Perf-regression observatory: one record per run (sky bench diff
    # compares against the committed history with noise-aware
    # thresholds, finally grounding vs_baseline in our own trajectory).
    try:
        from skypilot_tpu.observability import bench_history
        bench_history.append_record({
            'source': 'bench',
            'metric': _METRIC,
            'value': round(best_tps, 1),
            'unit': 'tokens/s',
            'config': {'model': config_name,
                       'device': dev.device_kind},
            'tokens_per_s': round(best_tps, 1),
            'mfu_estimate': round(mfu, 4),
        })
    except Exception as e:  # pylint: disable=broad-except
        print(f'# bench history append failed: {e}', file=sys.stderr)
    if on_tpu:
        # Feed the optimizer's fungibility prior with the measured MFU
        # (utils/throughput_registry; VERDICT r2 weak #8).
        from skypilot_tpu.utils import throughput_registry
        key = throughput_registry.device_kind_to_key(dev.device_kind)
        if key is not None:
            throughput_registry.record_measurement(
                key, mfu, tokens_per_sec=best_tps,
                model=f'{cfg_used.d_model}x{cfg_used.n_layers}'
                      f'/{config_name}')


def _attempt_envs():
    """(name, env, timeout_s) attempts, most capable platform first."""
    base = dict(os.environ)
    base['SKYTPU_BENCH_INNER'] = '1'
    base['PYTHONPATH'] = os.pathsep.join(
        p for p in (_REPO_ROOT, base.get('PYTHONPATH')) if p)
    yield 'ambient', dict(base), 1200

    stripped = {k: v for k, v in base.items()
                if k not in _PLUGIN_ENV_VARS}
    yield 'autodetect', dict(stripped), 600

    cpu = dict(stripped)
    cpu['JAX_PLATFORMS'] = 'cpu'
    yield 'cpu', cpu, 600


def _extract_json_line(stdout: bytes):
    for line in (stdout or b'').decode(errors='replace').splitlines():
        line = line.strip()
        if not line.startswith('{'):
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if parsed.get('metric'):
            return line
    return None


def orchestrate() -> None:
    for name, env, timeout_s in _attempt_envs():
        print(f'# bench attempt: {name}', file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, cwd=_REPO_ROOT, timeout=timeout_s,
                stdout=subprocess.PIPE, stderr=None)
            stdout, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as exc:
            # The inner run may have printed its result and then hung in
            # teardown (relay-down failure mode) — salvage it.
            stdout, rc = exc.stdout, f'timeout after {timeout_s}s'
        line = _extract_json_line(stdout)
        if line is not None:
            print(line)
            return
        print(f'# bench attempt {name}: rc={rc}, no JSON line',
              file=sys.stderr)
    # Last resort: every attempt failed — still emit a parseable line so
    # the round records a number instead of a crash.
    print(json.dumps({'metric': _METRIC, 'value': 0.0, 'unit': 'tokens/s',
                      'vs_baseline': 0.0, 'device': 'none',
                      'synced_timing': 'n/a'}))


if __name__ == '__main__':
    if os.environ.get('SKYTPU_BENCH_INNER'):
        main()
    else:
        orchestrate()
